"""Decode-step scheduler — iteration-level continuous batching for
generative serving (the Orca/vLLM scheduling idea, sized for the
seq2seq zoo's RNN decode state instead of a KV cache).

The stateless engine schedules at *request* granularity: a request
occupies its device-batch slot for exactly one predict.  A generative
request is a *sequence* — and scheduling those at request granularity
(``Seq2seq.infer``'s whole-sequence program) means a sequence that
finishes at step 5 still holds its slot for the full ``max_seq_len``
scan, and a short request's latency is gated by the longest co-rider.
This module schedules at *iteration* granularity instead:

* a :class:`DecodeSlotPool` holds per-sequence decode state — the
  RNN carries and last token — **device-resident** in fixed
  ``(capacity, ...)`` arrays, so state never round-trips the host
  between iterations;
* each scheduler iteration runs ONE decode step over the currently
  active slots, compacted through a ``slot_ids`` index vector that is
  bucket-padded on the PR 10 ladder — one AOT-warmed
  ``(batch_bucket, state_bucket=capacity)`` signature per rung, so no
  fill level ever recompiles, and the PR 8 persistent cache ships the
  step executable to replicas warm;
* a sequence that emits EOS (or exhausts its token budget) retires
  **between iterations**, freeing its slot, and the queue backfills
  the freed slot in the same scheduler iteration — the device batch
  is always as full as the traffic allows;
* every emitted token is surfaced immediately through the request's
  ``on_token`` callback — the per-token streaming hook the HTTP fast
  path's chunked ``/generate`` route rides.

The pool's two device programs are built through ``engine_jit``:

* ``prefill(params, tokens, carries, enc_ids[b,L], slot_ids[b])`` —
  run the model's encoder/bridge for ``b`` new sequences and scatter
  their initial state into the pool at ``slot_ids``;
* ``step(params, tokens, carries, slot_ids[b])`` — gather the active
  rows, run one ``decode_step``, scatter the updated state back, and
  emit the ``b`` new tokens (the iteration's single host transfer).

Bucket padding uses the out-of-range sentinel ``capacity`` with
scatter ``mode="drop"`` (padding lanes write nowhere) and gather
``mode="clip"`` (padding lanes compute garbage that is dropped on the
way back) — the same program serves every fill level of its bucket.

The model contract (``Seq2seq`` implements it) is four methods:
``decode_params()``, ``prefill(params, enc_ids)``,
``decode_step(params, tok, carries)``, ``initial_carries(batch)``.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.serving.engine.batcher import ShedError
from analytics_zoo_tpu.serving.engine.executor import (
    Endpoint, bucket_for, parse_buckets)

log = logging.getLogger("analytics_zoo_tpu.serving.engine")


def _mark(request, station: str, **attrs) -> None:
    """Record a reqtrace station for a traced request (no-op when the
    request carries no TraceContext or tracing is off)."""
    trace = getattr(request, "trace", None)
    if trace is None:
        return
    from analytics_zoo_tpu.observability.reqtrace import (
        get_request_log)
    get_request_log().mark(trace, station, **attrs)


@dataclasses.dataclass
class _ActiveSeq:
    """Host-side bookkeeping for one occupied slot (the device holds
    the actual decode state)."""
    request: Any                    # batcher.Request
    max_tokens: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    admitted_at: float = 0.0        # perf_counter at admission
    last_token_at: float = 0.0      # perf_counter at last emission


class DecodeSlotPool:
    """Device-resident per-sequence decode state + the bucketed
    per-step programs over it.

    NOT thread-safe by itself: the batcher's single executor thread is
    the only caller of :meth:`iterate` (the same single-dispatcher
    discipline the stateless executor runs under)."""

    def __init__(self, model, *, capacity: int, enc_len: int,
                 start_sign: int, stop_sign: Optional[int],
                 max_seq_len: int, buckets=()):
        import jax
        import jax.numpy as jnp

        from analytics_zoo_tpu.compile import engine_jit
        from analytics_zoo_tpu.observability import get_registry

        self.model = model
        self.capacity = int(capacity)
        self.enc_len = int(enc_len)
        self.start_sign = int(start_sign)
        self.stop_sign = None if stop_sign is None else int(stop_sign)
        self.max_seq_len = int(max_seq_len)
        self.buckets = parse_buckets(buckets, self.capacity)
        self._params = model.decode_params()
        # the pool: last token per slot + the model's carry pytree,
        # every leaf sized (capacity, ...) — resident for the pool's
        # whole life, updated in place by the step program's scatter
        self._tokens, self._carries = self._fresh_state()
        self._free: List[int] = list(range(self.capacity))
        self._active: Dict[int, _ActiveSeq] = {}
        self.iterations = 0            # device steps executed
        self.admitted_total = 0
        #: (iteration, slot) per admission/retirement — the test
        #: witness for "EOS-freed slot backfilled the same iteration"
        self.admit_log: List[tuple] = []
        self.retire_log: List[tuple] = []

        cap = self.capacity
        model_step = model.decode_step
        model_prefill = model.prefill

        def step_fn(params, tokens, carries, slot_ids):
            tok = jnp.take(tokens, slot_ids, mode="clip")
            sub = jax.tree_util.tree_map(
                lambda a: jnp.take(a, slot_ids, axis=0, mode="clip"),
                carries)
            nxt, new_sub = model_step(params, tok, sub)
            tokens = tokens.at[slot_ids].set(nxt, mode="drop")
            carries = jax.tree_util.tree_map(
                lambda full, rows: full.at[slot_ids].set(
                    rows, mode="drop"),
                carries, new_sub)
            return tokens, carries, nxt

        def prefill_fn(params, tokens, carries, enc_ids, slot_ids):
            new_sub = model_prefill(params, enc_ids)
            tok0 = jnp.full((enc_ids.shape[0],), self.start_sign,
                            jnp.int32)
            tokens = tokens.at[slot_ids].set(tok0, mode="drop")
            carries = jax.tree_util.tree_map(
                lambda full, rows: full.at[slot_ids].set(
                    rows, mode="drop"),
                carries, new_sub)
            return tokens, carries

        # pool state is donated: between iterations exactly ONE copy
        # of the decode state lives in HBM (MEM009's contract for
        # state rebound through a jit in a hot loop)
        self._step = engine_jit(
            step_fn, donate_argnums=(1, 2),
            key_hint=f"gen_decode_step_c{cap}")
        self._prefill = engine_jit(
            prefill_fn, donate_argnums=(1, 2),
            key_hint=f"gen_decode_prefill_c{cap}")

        reg = get_registry()
        self._m_tokens = reg.counter(
            "serving_tokens_total",
            "tokens emitted by the generative decode scheduler",
            labels=("endpoint",))
        self._m_steps = reg.counter(
            "serving_decode_steps_total",
            "decode-step device iterations executed",
            labels=("endpoint",))
        self._m_admitted = reg.counter(
            "serving_decode_admitted_total",
            "sequences admitted into the decode slot pool",
            labels=("endpoint",))
        self._m_retired = reg.counter(
            "serving_decode_retired_total",
            "sequences retired from the decode slot pool, by cause",
            labels=("endpoint", "cause"))
        self._m_occupancy = reg.gauge(
            "serving_slot_occupancy",
            "active decode slots / pool capacity",
            labels=("endpoint",))
        self._m_inter_token = reg.histogram(
            "serving_inter_token_latency_seconds",
            "gap between successive tokens of one sequence (the "
            "first gap is admission to first token)")
        self._m_first_token = reg.histogram(
            "serving_first_token_latency_seconds",
            "request arrival to first emitted token")
        self._endpoint_name = "?"   # set by GenerativeEndpoint

    # ------------------------------------------------------------ geometry
    def _fresh_state(self):
        """A brand-new device-resident pool state.  Every leaf is
        force-copied: the model's ``initial_carries`` may alias one
        zeros buffer across leaves (LSTM's ``(z, z)``), and the step
        program DONATES the pool state — the same buffer donated
        twice is an XLA runtime error."""
        import jax
        import jax.numpy as jnp
        tokens = jnp.full((self.capacity,), self.start_sign,
                          jnp.int32)
        carries = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True),
            self.model.initial_carries(self.capacity))
        return tokens, carries

    @property
    def active_count(self) -> int:
        return len(self._active)

    def bucket_for(self, n: int) -> int:
        return bucket_for(self.buckets, n)

    def _pad_ids(self, ids: List[int], bucket: int):
        # the sentinel ``capacity`` is out of range on purpose:
        # scatter mode="drop" makes padding lanes write nowhere.
        # Built as int32 NUMPY (a device_put, not a device
        # convert_element_type — the latter is a tiny per-shape XLA
        # compile that would break the zero-post-warm-compiles
        # contract in a fresh process)
        return np.asarray(
            ids + [self.capacity] * (bucket - len(ids)), np.int32)

    # ----------------------------------------------------------- warm start
    def warm(self) -> int:
        """AOT warm-start every ``(batch_bucket, capacity)`` rung of
        BOTH pool programs (step + prefill) — deserialized from the
        persistent executable cache when one is configured.  After a
        full warm, no fill level compiles.  Returns #programs
        readied."""
        import jax.numpy as jnp
        warmed = 0
        for b in self.buckets:
            ids = jnp.zeros((b,), jnp.int32)
            enc = jnp.zeros((b, self.enc_len), jnp.int32)
            try:
                warmed += bool(self._step.warm(
                    self._params, self._tokens, self._carries, ids))
                warmed += bool(self._prefill.warm(
                    self._params, self._tokens, self._carries, enc,
                    ids))
            except Exception:   # noqa: BLE001 — warm is best-effort
                log.exception("decode warm-up failed for bucket %d",
                              b)
        return warmed

    @property
    def aot_signatures(self) -> int:
        return (self._step.aot_signatures
                + self._prefill.aot_signatures)

    # ------------------------------------------------------------ admission
    def admit(self, requests: List, now: Optional[float] = None
              ) -> int:
        """Prefill + scatter up to ``len(self._free)`` new sequences
        into free slots (one bucket-padded prefill program call).
        Returns #admitted; the rest stay with the caller."""
        n = min(len(requests), len(self._free))
        if n == 0:
            return 0
        now = time.perf_counter() if now is None else now
        batch = requests[:n]
        slots = [self._free.pop(0) for _ in range(n)]
        bucket = self.bucket_for(n)
        enc = np.zeros((bucket, self.enc_len), np.int32)
        for i, r in enumerate(batch):
            row = np.asarray(r.data, np.int32).reshape(-1)
            if row.shape[0] != self.enc_len:
                # contract: fixed enc_len per endpoint (clients pad);
                # clamp/pad here so one odd record cannot poison the
                # whole pool program
                padded = np.zeros(self.enc_len, np.int32)
                padded[:min(row.shape[0], self.enc_len)] = \
                    row[:self.enc_len]
                row = padded
            enc[i] = row
        ids = self._pad_ids(slots, bucket)
        try:
            self._tokens, self._carries = self._prefill(
                self._params, self._tokens, self._carries,
                np.asarray(enc), ids)
        except BaseException as e:   # noqa: BLE001 — containment
            # a failed prefill fails exactly the batch it was
            # admitting — and CONSUMES it (the caller pops it off the
            # queue), because re-queueing a deterministically-poison
            # group would fail every future iteration forever.  The
            # device state may hold consumed donated buffers: rebuild.
            self._tokens, self._carries = self._fresh_state()
            self._free = sorted(set(self._free) | set(slots))
            for r in batch:
                self._m_retired.labels(self._endpoint_name,
                                       "error").inc()
                if not r.done:
                    r.fail(e)
            log.exception("prefill failed; %d admitting sequence(s) "
                          "failed and consumed", n)
            if not isinstance(e, Exception):
                raise      # process-death class: PEL-reclaim contract
            return n
        for r, slot in zip(batch, slots):
            budget = self.max_seq_len
            if getattr(r, "max_tokens", None):
                budget = max(1, min(int(r.max_tokens),
                                    self.max_seq_len))
            self._active[slot] = _ActiveSeq(
                request=r, max_tokens=budget, admitted_at=now,
                last_token_at=now)
            self.admit_log.append((self.iterations, slot))
            _mark(r, "prefill", t=now, slot=slot, bucket=bucket)
        self.admitted_total += n
        self._m_admitted.labels(self._endpoint_name).inc(n)
        self._m_occupancy.labels(self._endpoint_name).set(
            len(self._active) / self.capacity)
        return n

    # ------------------------------------------------------------ iteration
    def step_once(self) -> int:
        """One decode iteration over the active slots: gather → step →
        scatter → emit.  Retires EOS/budget-exhausted sequences and
        frees their slots.  Returns #tokens emitted."""
        # sweep abandoned sequences first: a transport that timed a
        # request out already answered its client — decoding its
        # remaining tokens would burn device steps on a response
        # nobody reads (the generative twin of the batcher's
        # compose-time drop)
        for slot in [s for s, seq in self._active.items()
                     if seq.request.done]:
            seq = self._active.pop(slot)
            self._free.append(slot)
            self.retire_log.append((self.iterations, slot))
            self._m_retired.labels(self._endpoint_name,
                                   "abandoned").inc()
        if not self._active:
            self._m_occupancy.labels(self._endpoint_name).set(0.0)
            return 0
        slots = sorted(self._active)
        bucket = self.bucket_for(len(slots))
        ids = self._pad_ids(slots, bucket)
        self._tokens, self._carries, emitted = self._step(
            self._params, self._tokens, self._carries, ids)
        emitted = np.asarray(emitted)     # the iteration's ONE sync
        self.iterations += 1
        now = time.perf_counter()
        self._m_steps.labels(self._endpoint_name).inc()
        n_emitted = len(slots)
        self._m_tokens.labels(self._endpoint_name).inc(n_emitted)
        for lane, slot in enumerate(slots):
            seq = self._active[slot]
            tok = int(emitted[lane])
            first = not seq.tokens
            seq.tokens.append(tok)
            self._m_inter_token.observe(now - seq.last_token_at)
            if first:
                self._m_first_token.observe(
                    now - (seq.request.arrival or seq.admitted_at))
            seq.last_token_at = now
            _mark(seq.request, "decode_step", t=now,
                  iteration=self.iterations,
                  token_index=len(seq.tokens) - 1)
            cb = getattr(seq.request, "on_token", None)
            if cb is not None:
                try:
                    cb(len(seq.tokens) - 1, tok)
                except Exception:   # noqa: BLE001 — streaming is
                    pass            # best-effort, decode is not
            if (self.stop_sign is not None
                    and tok == self.stop_sign):
                self._retire(slot, "eos")
            elif len(seq.tokens) >= seq.max_tokens:
                self._retire(slot, "max_tokens")
        self._m_occupancy.labels(self._endpoint_name).set(
            len(self._active) / self.capacity)
        return n_emitted

    def _retire(self, slot: int, cause: str) -> None:
        seq = self._active.pop(slot)
        self._free.append(slot)
        self.retire_log.append((self.iterations, slot))
        self._m_retired.labels(self._endpoint_name, cause).inc()
        _mark(seq.request, "retire", cause=cause,
              tokens=len(seq.tokens))
        seq.request.complete(list(seq.tokens))

    # -------------------------------------------------------------- failure
    def fail_all(self, exc: BaseException) -> int:
        """The generative poison contract: the active sequences share
        one fused device program, so a failed iteration fails them ALL
        (each request carries the error to its transport) and the pool
        resets to empty — the endpoint is never wedged on corrupt
        state."""
        n = len(self._active)
        for slot, seq in list(self._active.items()):
            self._m_retired.labels(self._endpoint_name, "error").inc()
            if not seq.request.done:
                seq.request.fail(exc)
        self._active.clear()
        self._free = list(range(self.capacity))
        # the failed call may have consumed the donated state buffers
        # before raising — rebuild, don't reuse
        self._tokens, self._carries = self._fresh_state()
        self._m_occupancy.labels(self._endpoint_name).set(0.0)
        return n


class GenerativeEndpoint(Endpoint):
    """A served *generative* model: a queue of sequences + the decode
    slot pool the scheduler iterates.  The batcher treats it like any
    endpoint for scheduling credits, but routes it through
    ``ModelExecutor.execute_decode`` (one decode ITERATION per credit)
    instead of the stateless batch compose."""

    generative = True

    def __init__(self, name: str, model, *, enc_len: int,
                 start_sign: int, stop_sign: Optional[int] = None,
                 max_seq_len: int = 32, slots: int = 4,
                 buckets=(), weight: int = 1,
                 request_deadline_ms: float = 0.0):
        super().__init__(name, model, top_n=1, buckets=buckets,
                         batch_size=slots,
                         input_shape=(int(enc_len),), weight=weight)
        self.pool = DecodeSlotPool(
            model, capacity=int(slots), enc_len=int(enc_len),
            start_sign=start_sign, stop_sign=stop_sign,
            max_seq_len=int(max_seq_len), buckets=self.buckets)
        self.pool._endpoint_name = name
        self.max_seq_len = int(max_seq_len)
        # generative admission control (the PR 9 shed contract, which
        # /generate and Redis generative groups used to bypass): a
        # sequence still QUEUED — not yet admitted into a slot — past
        # request_deadline_ms is shed before it burns a slot.  An
        # ADMITTED sequence is never shed: its slot is already paid
        # for and tokens may already be on the wire.  0 disables.
        self.request_deadline_ms = float(request_deadline_ms or 0.0)
        from analytics_zoo_tpu.observability import get_registry
        self._m_shed = get_registry().counter(
            "serving_shed_total",
            "records shed by admission control instead of predicted",
            labels=("cause",))

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.pool.active_count)

    def warm(self) -> int:
        """Warm the full (batch_bucket, state_bucket) program ladder
        instead of the stateless predict rungs."""
        return self.pool.warm()

    # ----------------------------------------------------------- scheduling
    def backfill(self) -> int:
        """Admit queued sequences into free slots (whole queue-order,
        skipping requests a transport already timed out).  Queue pops
        are GIL-atomic deque ops — submit() appends under the
        batcher's lock, the executor thread pops here without it, the
        deque itself is the synchronization point."""
        self.shed_expired()
        admitted = 0
        while self.queue and self.pool._free:
            group = self.queue[0]
            live = [r for r in group if not r.done]
            if not live:
                self.queue.popleft()
                continue
            n = self.pool.admit(live)
            admitted += n
            if n < len(live):
                # pool full mid-group: keep the remainder queued
                group[:] = live[n:]
                break
            self.queue.popleft()
        return admitted

    def shed_expired(self) -> int:
        """Generative admission control (the PR 9 shed contract,
        which ``/generate`` and Redis generative groups used to
        bypass): a sequence still QUEUED — not yet admitted into a
        slot — past ``request_deadline_ms`` is failed with
        :class:`~.batcher.ShedError` and counted under
        ``serving_shed_total{cause="deadline"}`` before it burns a
        slot.  Runs every scheduler iteration, full pool included:
        that is exactly when queue waits age sequences out, and the
        client deserves its 504 now, not when a slot finally frees.
        An ADMITTED sequence is never shed — its slot is already paid
        for and tokens may already be on the wire.  Returns #shed."""
        ddl_s = self.request_deadline_ms / 1000.0
        if ddl_s <= 0 or not self.queue:
            return 0
        now = time.perf_counter()
        shed = 0
        for group in list(self.queue):
            for r in group:
                if r.done or not r.arrival \
                        or now - r.arrival <= ddl_s:
                    continue
                age_ms = (now - r.arrival) * 1e3
                self._m_shed.labels("deadline").inc()
                shed += 1
                r.fail(ShedError(
                    f"shed: deadline ({age_ms:.0f}ms queued, "
                    f"deadline {self.request_deadline_ms:.0f}ms) — "
                    f"sequence never admitted",
                    age_ms=age_ms,
                    deadline_ms=self.request_deadline_ms))
        return shed

    def run_iteration(self) -> int:
        """One scheduler iteration: step the active slots, retire
        finished sequences, and backfill the freed slots from the
        queue in the SAME iteration.  Returns #tokens emitted +
        #sequences admitted (0 = no work left)."""
        emitted = self.pool.step_once()
        admitted = self.backfill()
        if emitted == 0 and admitted:
            # freshly admitted into an idle pool: run their first
            # step now rather than waiting for the next credit —
            # first-token latency is the point of the fast path
            emitted = self.pool.step_once()
        return emitted + admitted
