"""Serving engine v2 — the transport / batcher / executor split.

The PR 9 serving tier was one fixed-size-batch loop: read a full
``batch_size`` from the Redis stream, decode, predict, write.  This
package decomposes serving into three independently testable layers
(the refactor ROADMAP item 1 names):

* **transport** — where requests come from and results go back.  The
  existing Redis-stream bulk path (``ClusterServing`` remains that
  transport's composition root) plus a new stdlib HTTP/JSON
  low-latency fast path (:class:`HttpTransport`).  Both feed ONE
  shared request queue, so an HTTP single rides the same device batch
  as a Redis bulk group.
* **batcher** — :class:`ContinuousBatcher`: continuous / in-flight
  batching.  The moment the executor frees, a batch is formed from
  whatever is queued and padded to the nearest of a small set of
  AOT-warmed bucket sizes (instead of always ``batch_size``); the
  ``max_wait_ms`` knob bounds how long a lone request may wait for
  co-riders, so it never stalls.
* **executor** — :class:`EndpointRegistry` + :class:`ModelExecutor`:
  a multi-model endpoint registry (endpoint name →
  ``InferenceModel`` + warmed executables), per-endpoint queues with
  weighted scheduling, and per-bucket AOT warm-up at model load (the
  PR 8 ``compile/`` cache makes a replica respawn deserialize in
  seconds).

A fourth layer serves *generative* models at **iteration**
granularity (:mod:`.decode`): :class:`GenerativeEndpoint` holds a
device-resident :class:`DecodeSlotPool` of per-sequence decode state,
the batcher hands it one decode STEP per scheduling credit, EOS (or a
per-request token budget) retires a sequence between iterations with
its freed slot backfilled from the queue the same iteration, and
every token streams out through the request's ``on_token`` hook — the
Orca/vLLM-style scheduling that turns the engine from "stateless
predict at request granularity" into a generative serving stack.

:class:`ServingEngine` composes the layers for embedders.
"""

from analytics_zoo_tpu.serving.engine.batcher import (
    ContinuousBatcher, Request)
from analytics_zoo_tpu.serving.engine.executor import (
    Endpoint, EndpointRegistry, ModelExecutor, default_buckets)
from analytics_zoo_tpu.serving.engine.core import ServingEngine
from analytics_zoo_tpu.serving.engine.decode import (
    DecodeSlotPool, GenerativeEndpoint)
from analytics_zoo_tpu.serving.engine.transport import HttpTransport

__all__ = [
    "ContinuousBatcher", "Request", "Endpoint", "EndpointRegistry",
    "ModelExecutor", "ServingEngine", "HttpTransport",
    "DecodeSlotPool", "GenerativeEndpoint",
    "default_buckets",
]
