"""ServingEngine — the composition root of the three layers.

Transports (the Redis loop in ``serving.server``, the HTTP fast path
in ``engine.transport``, or any embedder) share one engine: they
build :class:`~.batcher.Request` objects, ``submit()`` them as atomic
groups, and wait for completion.  The batcher thread does every
predict, so requests from different transports co-ride the same
bucket-padded device batches.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence

from analytics_zoo_tpu.serving.engine.batcher import (
    ContinuousBatcher, Request)
from analytics_zoo_tpu.serving.engine.executor import (
    EndpointRegistry, ModelExecutor)

log = logging.getLogger("analytics_zoo_tpu.serving.engine")

#: the endpoint a record with no ``endpoint`` field routes to — and
#: the name a single-model ``ClusterServing`` registers its model as
DEFAULT_ENDPOINT = "default"


class ServingEngine:
    """Endpoint registry + executor + continuous batcher, one handle."""

    def __init__(self, *, max_wait_ms: float = 0.0,
                 default_timeout_s: float = 60.0):
        self.registry = EndpointRegistry()
        self.executor = ModelExecutor()
        self.batcher = ContinuousBatcher(
            self.registry, self.executor, max_wait_ms=max_wait_ms)
        #: upper bound a transport waits on a submitted request before
        #: declaring it failed (guards client threads against a dead
        #: batcher — generous: a cold compile may hide behind it)
        self.default_timeout_s = float(default_timeout_s)

    # ------------------------------------------------------------ endpoints
    def register(self, name: str, model, **kwargs):
        """Register a model under an endpoint name (see
        :class:`~.executor.Endpoint` for kwargs: top_n, buckets,
        batch_size, input_shape, weight)."""
        return self.registry.register(name, model, **kwargs)

    def register_generative(self, name: str, model, *, enc_len: int,
                            start_sign: int,
                            stop_sign: Optional[int] = None,
                            max_seq_len: int = 32, slots: int = 4,
                            buckets=(), weight: int = 1,
                            request_deadline_ms: float = 0.0):
        """Register a *generative* model (the ``Seq2seq`` decode
        contract: ``decode_params``/``prefill``/``decode_step``/
        ``initial_carries``) under an endpoint name.  Requests to it
        are SEQUENCES — admitted into a device-resident slot pool and
        decoded one iteration at a time, with EOS early-exit and
        same-iteration backfill (see ``engine.decode``).  ``slots``
        sizes the pool (the generative analog of ``batch_size``);
        ``request_deadline_ms`` > 0 sheds sequences still queued past
        the deadline before they burn a slot (the stateless path's
        admission-control contract, applied at the slot-pool gate)."""
        from analytics_zoo_tpu.serving.engine.decode import (
            GenerativeEndpoint)
        return self.registry.add(GenerativeEndpoint(
            name, model, enc_len=enc_len, start_sign=start_sign,
            stop_sign=stop_sign, max_seq_len=max_seq_len, slots=slots,
            buckets=buckets, weight=weight,
            request_deadline_ms=request_deadline_ms))

    def endpoints(self) -> List[str]:
        return self.registry.names()

    def warm_start(self) -> Dict[str, int]:
        """AOT-warm every endpoint's full bucket ladder."""
        return self.registry.warm_all()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingEngine":
        self.batcher.start()
        return self

    def stop(self) -> None:
        self.batcher.stop()

    @property
    def alive(self) -> bool:
        return self.batcher.alive

    # -------------------------------------------------------------- serving
    def submit(self, requests: Sequence[Request]) -> List[Request]:
        """Enqueue one atomic group (auto-starts the batcher — a
        stopped engine must fail loud-and-finished, not hang its
        transports)."""
        if not self.batcher.alive:
            self.start()
        return self.batcher.submit(requests)

    def wait_all(self, requests: Sequence[Request],
                 timeout_s: Optional[float] = None) -> List[Request]:
        """Block until every request completes under ONE deadline;
        stragglers are failed with :class:`TimeoutError` (they are
        also dropped by the batcher at compose time, so a timed-out
        request never burns a device predict later).  Shared by
        ``submit_wait`` and the Redis transport."""
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        requests = list(requests)
        deadline = time.monotonic() + timeout_s
        for r in requests:
            if not r.wait(max(deadline - time.monotonic(), 0.0)) \
                    and not r.done:
                r.fail(TimeoutError(
                    f"serving engine gave no result within "
                    f"{timeout_s:.1f}s (endpoint {r.endpoint!r})"))
        return requests

    def submit_wait(self, requests: Sequence[Request],
                    timeout_s: Optional[float] = None
                    ) -> List[Request]:
        """Submit a group and block until every request completes (or
        the deadline passes — see :meth:`wait_all`)."""
        return self.wait_all(self.submit(requests),
                             timeout_s=timeout_s)

    def predict(self, endpoint: str, data, *,
                uri: str = "", request_id: Optional[str] = None,
                timeout_s: Optional[float] = None):
        """One-record convenience (the HTTP fast path's core): returns
        the top-N result or raises the request's error."""
        req = Request(endpoint=endpoint, uri=uri, data=data,
                      request_id=request_id)
        self.submit_wait([req], timeout_s=timeout_s)
        if req.error is not None:
            raise req.error
        return req.result

    def generate(self, endpoint: str, enc_ids, *,
                 max_tokens: Optional[int] = None,
                 on_token=None, uri: str = "",
                 request_id: Optional[str] = None,
                 timeout_s: Optional[float] = None) -> List[int]:
        """One-sequence convenience against a generative endpoint:
        returns the emitted token list (EOS included when emitted).
        ``on_token(index, token)`` streams each token as the decode
        scheduler emits it."""
        req = Request(endpoint=endpoint, uri=uri, data=enc_ids,
                      request_id=request_id, max_tokens=max_tokens,
                      on_token=on_token)
        self.submit_wait([req], timeout_s=timeout_s)
        if req.error is not None:
            raise req.error
        return req.result
