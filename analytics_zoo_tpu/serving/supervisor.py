"""Serving replica supervisor — the resilience layer of the fleet.

The reference's Cluster Serving got multi-replica fault tolerance for
free from Spark executor restart + Redis consumer groups (BigDL,
arXiv 1804.05839 §serving; BigDL 2.0, arXiv 2204.01715).  Our rebuild
provides — and beats — that property itself: a
:class:`ServingSupervisor` spawns N ``ClusterServing`` worker
processes against ONE consumer group (distinct consumer names, so each
record is delivered to exactly one replica and a dead replica's
un-acked records are PEL-reclaimed by its peers), watches them via

* **process exit** — classified with
  :func:`~analytics_zoo_tpu.resilience.detector.classify_exit`
  (``ok`` / ``error(N)`` / ``signal(SIGKILL)`` …),
* **/healthz** — each replica publishes its metrics port through
  ``ZOO_TPU_SERVING_PORT_FILE``; 200/503 both mean *alive* (503 =
  not-ready, e.g. ``breaker_open`` during a broker outage — restarting
  cannot fix that, so the supervisor deliberately does NOT),
* **heartbeats** — with a ``run_dir``, replicas write the PR 6
  ``host-<k>/heartbeat.json``; a staleness past
  ``resilience.heartbeat_timeout_s`` flags a replica whose process
  still polls as running but whose serve loop is wedged,

and restarts crashed replicas with exponential backoff under a
:class:`~analytics_zoo_tpu.resilience.policy.RetryBudget` (the
reference's time-windowed budget).  Budget exhausted → the fleet ends
*structured*: a ``DegradedTraining``-style record (mirrored to
``<run_dir>/degraded.json``) and
:data:`~analytics_zoo_tpu.resilience.policy.DEGRADED_EXIT_CODE` (17)
from the CLI — honoring the ``zoo-launch --max-degraded`` contract, so
an orchestrator can tell "serving tier gave up in an orderly way"
from "supervisor crashed".

SIGTERM to the supervisor drains the fleet gracefully: each replica
gets SIGTERM, finishes + acks its in-flight batches, flushes metrics,
and exits 0 (escalating to SIGKILL only past ``drain_timeout_s``).

**SLO-driven autoscaling** (``min_replicas``/``max_replicas``): the
supervisor already polls every replica's /healthz port — the same
``MetricsServer`` serves ``/metrics.json``, so the fleet's own
exported signals drive scale decisions with zero new plumbing:

* **up** — the shared-stream backlog (``serving_queue_depth``, the
  PR 1 gauge every replica exports) sustained above
  ``scale_up_queue_depth`` for ``scale_up_sustain_s``, or p50 request
  latency (the PR 1 histogram) sustained above
  ``scale_up_latency_p50_ms`` when that knob is set;
* **down** — backlog empty for ``scale_down_idle_s`` (fill ratio and
  latency ride every scale event's signal record for forensics, but
  the LIVE backlog is the decisive idle signal — the fill gauge
  holds the last batch's value and would read stale-high forever on
  an idle fleet): the highest-index replica is *retired* — SIGTERM,
  the existing drain contract, so it finishes + acks in-flight
  batches and exits 0 — and never restarted;
* **hysteresis** — both signals must SUSTAIN (one noisy poll never
  scales), and ``scale_cooldown_s`` separates consecutive scale
  events so a fresh replica gets to absorb load before the next
  decision;
* **held** — a replica 503ing ``error_rate`` pauses scale-up: a
  poisoned stream amplified across more replicas is more poison, not
  more throughput.

The live fleet size is exported as ``serving_fleet_replicas`` and
recorded in ``replica_trajectory`` — the autoscaler's acceptance
evidence.

The supervisor process never touches a device — replicas are separate
processes, so the fleet controller can run on a host with no
accelerator access at all.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.fsutil import atomic_write_text
from analytics_zoo_tpu.observability import get_registry
from analytics_zoo_tpu.observability import flightrec as _flightrec
from analytics_zoo_tpu.resilience.detector import (
    classify_exit, read_heartbeats)
from analytics_zoo_tpu.resilience.policy import (
    DEGRADED_EXIT_CODE, DegradedTraining, RetryBudget, degraded_exit)

log = logging.getLogger("analytics_zoo_tpu.serving.supervisor")

#: env var through which a replica publishes its bound /metrics
#: (+/healthz) port back to the supervisor (server._publish_port)
ENV_PORT_FILE = "ZOO_TPU_SERVING_PORT_FILE"

#: worker_factory signature: (replica_index, incarnation) ->
#: (argv list, extra env dict) — incarnation 0 is the first spawn,
#: so tests can arm chaos for exactly one life of a replica
WorkerFactory = Callable[[int, int], Tuple[List[str], Dict[str, str]]]


def _set_pdeathsig():   # pragma: no cover — linux only
    """Replica dies with the supervisor (launcher._set_pdeathsig's
    role, re-implemented here so this module never imports the
    jax-adjacent parallel package)."""
    try:
        import ctypes
        import signal
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:   # noqa: BLE001 — non-linux
        pass


def cli_worker_factory(config_path: str,
                       consumer_group: str = "serving",
                       extra_args: Tuple[str, ...] = (),
                       python: str = sys.executable) -> WorkerFactory:
    """The default replica command: ``zoo-serving start`` against one
    shared consumer group, a unique consumer name per replica slot,
    and an ephemeral metrics port (the replica publishes the bound
    port back through :data:`ENV_PORT_FILE`)."""
    def factory(index: int, incarnation: int):
        cmd = [python, "-m", "analytics_zoo_tpu.serving.cli", "start",
               "-c", config_path,
               "--consumer-group", consumer_group,
               "--consumer-name", f"replica-{index}",
               "--metrics-port", "0", *extra_args]
        return cmd, {}
    return factory


@dataclasses.dataclass
class _Replica:
    """Supervisor-side state of one replica slot."""
    index: int
    port_file: str
    budget: RetryBudget
    proc: Optional[subprocess.Popen] = None
    incarnation: int = 0          # lives spawned so far
    port: Optional[int] = None    # discovered /healthz port
    spawned_at: float = 0.0
    next_spawn_at: Optional[float] = None   # backoff restart schedule
    consecutive_failures: int = 0
    health_fails: int = 0         # consecutive unreachable probes
    last_health_at: float = 0.0
    last_exit: Optional[int] = None
    done: bool = False            # exited 0 (orderly drain)
    degraded: bool = False        # exited DEGRADED_EXIT_CODE
    kill_reason: Optional[str] = None   # supervisor-initiated kill
    retiring: bool = False        # scale-down drain in progress
    retire_deadline: float = 0.0  # monotonic: SIGKILL escalation
    last_health: str = ""         # latest _probe result


class ServingSupervisor:
    """Spawn, watch, restart, and drain a fleet of serving replicas.

    ``run()`` blocks until the fleet drains (``stop()`` / SIGTERM /
    every replica exiting 0 or 17) and returns a summary dict — or
    raises :class:`DegradedTraining` when a replica exhausts its
    restart budget (the CLI maps that to exit 17 via
    ``degraded_exit``)."""

    def __init__(self, worker_factory: WorkerFactory,
                 replicas: int = 3, *,
                 retry_times: Optional[int] = None,
                 retry_window_s: Optional[float] = None,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 10.0,
                 health_interval_s: float = 2.0,
                 health_fail_threshold: int = 3,
                 startup_grace_s: float = 30.0,
                 heartbeat_timeout_s: Optional[float] = None,
                 run_dir: Optional[str] = None,
                 drain_timeout_s: float = 30.0,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 scale_up_queue_depth: Optional[int] = None,
                 scale_up_latency_p50_ms: Optional[float] = None,
                 scale_up_sustain_s: Optional[float] = None,
                 scale_down_idle_s: Optional[float] = None,
                 scale_cooldown_s: Optional[float] = None,
                 autoscale_interval_s: float = 1.0,
                 slo_signal: Optional[Callable[[], Dict]] = None,
                 clock: Optional[Callable[[], float]] = None):
        if retry_times is None:
            retry_times = int(get_config().get(
                "serving.supervisor_retry_times", 5))
        if retry_window_s is None:
            retry_window_s = float(get_config().get(
                "serving.supervisor_retry_window_s", 60.0))
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(get_config().get(
                "resilience.heartbeat_timeout_s", 30.0))
        # every interval/hysteresis decision reads THIS clock (default
        # monotonic): tests drive scale mechanics deterministically by
        # injecting a fake clock and calling _tick() directly, instead
        # of racing wall-clock sustain windows against a loaded CPU
        self._clock = clock or time.monotonic
        self.worker_factory = worker_factory
        self.replicas = int(replicas)
        self.retry_times = int(retry_times)
        self.retry_window_s = float(retry_window_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.health_interval_s = float(health_interval_s)
        self.health_fail_threshold = max(int(health_fail_threshold), 1)
        self.startup_grace_s = float(startup_grace_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.run_dir = run_dir
        # ---- autoscaler: bounds + SLO knobs (config-defaulted) -----
        cfg = get_config()
        if scale_up_queue_depth is None:
            scale_up_queue_depth = int(cfg.get(
                "serving.scale_up_queue_depth", 16))
        if scale_up_latency_p50_ms is None:
            scale_up_latency_p50_ms = float(cfg.get(
                "serving.scale_up_latency_p50_ms", 0.0))   # 0 = off
        if scale_up_sustain_s is None:
            scale_up_sustain_s = float(cfg.get(
                "serving.scale_up_sustain_s", 3.0))
        if scale_down_idle_s is None:
            scale_down_idle_s = float(cfg.get(
                "serving.scale_down_idle_s", 10.0))
        if scale_cooldown_s is None:
            scale_cooldown_s = float(cfg.get(
                "serving.scale_cooldown_s", 5.0))
        self.min_replicas = int(replicas if min_replicas is None
                                else min_replicas)
        self.max_replicas = int(replicas if max_replicas is None
                                else max_replicas)
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f"min_replicas {self.min_replicas} > max_replicas "
                f"{self.max_replicas}")
        self.autoscale = self.max_replicas > self.min_replicas
        self.scale_up_queue_depth = int(scale_up_queue_depth)
        self.scale_up_latency_p50_ms = float(scale_up_latency_p50_ms)
        self.scale_up_sustain_s = float(scale_up_sustain_s)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.autoscale_interval_s = float(autoscale_interval_s)
        # SLO feed (observability/slo.py): a callable returning the
        # latest {"alert": "ok"|"warn"|"page", "budget_remaining":
        # float} for the serving SLO.  A paging burn rate is a
        # scale-up pressure source in its own right (the budget is
        # burning faster than queue depth alone admits), and an
        # exhausted error budget HOLDS scale-down — retiring capacity
        # while the budget is spent converts a warn into an outage.
        self._slo_signal = slo_signal
        # hysteresis state: when each condition STARTED holding
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_scale_at = 0.0
        self._last_autoscale_poll = 0.0
        # fleet bookkeeping (_replicas, scale_events,
        # replica_trajectory, restarts_total, the autoscale clock) is
        # mutated on the supervision loop thread while summary()/
        # wait_ready()/drain_fleet() read from the caller's thread —
        # every touch holds this lock.  Reentrant because
        # _record_fleet_size → _persist_state nest read sections
        # inside write sections.  NEVER held across _spawn or a
        # health probe: iteration sites copy the list and release.
        self._fleet_lock = threading.RLock()
        #: [(unix time, fleet size, reason)] — every size change,
        #: including the initial spawn; the acceptance trajectory
        self.replica_trajectory: List[Tuple[float, int, str]] = []
        self.scale_events: List[Dict] = []
        # ``replicas`` is the INITIAL size, clamped into bounds
        self.replicas = min(max(self.replicas, self.min_replicas),
                            self.max_replicas)
        self._state_dir = run_dir or tempfile.mkdtemp(
            prefix="zoo-serving-supervisor-")
        os.makedirs(self._state_dir, exist_ok=True)
        # the control plane's own flight recorder: lifecycle events
        # journal to <run_dir>/events.jsonl (ring-only without a run
        # dir) — deliberately a PRIVATE instance, the process-wide
        # slot belongs to worker processes
        self._flightrec = _flightrec.FlightRecorder(
            run_dir, role="supervisor")
        self._replicas: List[_Replica] = [
            _Replica(index=i,
                     port_file=os.path.join(self._state_dir,
                                            f"replica-{i}.port"),
                     budget=RetryBudget(self.retry_times,
                                        self.retry_window_s))
            for i in range(self.replicas)]
        self.restarts_total = 0
        self._stop = threading.Event()
        reg = get_registry()
        self._m_running = reg.gauge(
            "serving_replicas_running",
            "serving replicas currently alive under the supervisor")
        self._m_restarts = reg.counter(
            "serving_replica_restarts_total",
            "replica restarts performed by the supervisor")
        self._m_exits = reg.counter(
            "serving_replica_exits_total",
            "replica exits observed, by classified exit code",
            labels=("class",))
        self._m_fleet = reg.gauge(
            "serving_fleet_replicas",
            "live (non-retiring) serving replicas the autoscaler is "
            "holding the fleet at")
        self._m_scale = reg.counter(
            "serving_scale_events_total",
            "autoscaler scale decisions", labels=("direction",))
        self._m_slo_hold = reg.counter(
            "serving_slo_hold_total",
            "autoscaler decisions vetoed by the SLO signal, by kind",
            labels=("kind",))
        self._record_fleet_size("initial")

    # -------------------------------------------------------------- control
    def stop(self) -> None:
        """Request a graceful fleet drain (also the SIGTERM handler)."""
        self._stop.set()

    def install_signal_handlers(self) -> bool:
        """SIGTERM → ``stop()`` (graceful drain).  Main-thread only;
        returns False when not installable."""
        import signal
        try:
            signal.signal(signal.SIGTERM,
                          lambda _sig, _frame: self.stop())
            return True
        except ValueError:
            return False

    # ------------------------------------------------------------ lifecycle
    def _spawn(self, r: _Replica) -> None:
        cmd, env = self.worker_factory(r.index, r.incarnation)
        full = dict(os.environ)
        full.update(env or {})
        try:
            os.remove(r.port_file)     # stale port from a past life
        except OSError:
            pass
        if self.run_dir:
            # drop the dead incarnation's heartbeat too (the same
            # contamination guard the launcher applies to reused run
            # dirs): the replacement's first beat only lands after
            # model load, and judging it by its predecessor's stale
            # timestamp would kill every slow-starting respawn until
            # the budget spuriously degrades the fleet
            try:
                os.remove(os.path.join(self.run_dir,
                                       f"host-{r.index}",
                                       "heartbeat.json"))
            except OSError:
                pass
        r.port = None
        r.health_fails = 0
        r.kill_reason = None
        full[ENV_PORT_FILE] = r.port_file
        # chaos process filtering + const metric labels both key on
        # the replica slot; a test factory may override
        full.setdefault("ZOO_TPU_PROCESS_ID", str(r.index))
        if self.run_dir:
            slot = os.path.join(self.run_dir, f"host-{r.index}")
            os.makedirs(slot, exist_ok=True)
            full.setdefault("ZOO_TPU_METRICS_DIR", slot)
        r.proc = subprocess.Popen(cmd, env=full,
                                  preexec_fn=_set_pdeathsig)
        r.incarnation += 1
        r.spawned_at = self._clock()
        r.next_spawn_at = None
        self._flightrec.record(
            "replica.spawn", replica=r.index,
            incarnation=r.incarnation, pid=r.proc.pid)
        log.info("replica %d spawned (incarnation %d, pid %d)",
                 r.index, r.incarnation, r.proc.pid)

    def _handle_exit(self, r: _Replica, code: int) -> None:
        r.proc = None
        r.last_exit = code
        killed, r.kill_reason = r.kill_reason, None
        if r.retiring:
            # scale-down retirement: whatever the exit code, this slot
            # is finished — respawning it would undo the scale
            # decision.  (A non-zero exit during drain is logged: the
            # records it read are in the PEL for its peers.)
            r.done = True
            if code == 0:
                log.info("replica %d retired (scale-down drain, "
                         "exit 0)", r.index)
            else:
                log.warning("replica %d exited %d during scale-down "
                            "drain; peers will reclaim its PEL",
                            r.index, code)
            self._m_exits.labels("retired").inc()
            self._flightrec.record(
                "replica.retire", replica=r.index, exit=code)
            return
        cls = ("killed_by_supervisor" if killed
               else "degraded" if code == DEGRADED_EXIT_CODE
               else classify_exit(code))
        self._m_exits.labels(cls).inc()
        self._flightrec.record(
            "replica.exit", replica=r.index, exit=code,
            classification=cls,
            **({"kill_reason": killed} if killed else {}))
        # a supervisor-initiated kill (wedged heartbeat, unreachable
        # /healthz) must be restarted no matter HOW the replica ended:
        # its SIGTERM handler drains gracefully to exit 0, and taking
        # that as an orderly retirement would silently shrink the
        # fleet with no restart and no degraded record
        if killed:
            log.warning("replica %d exited %d after supervisor kill "
                        "(%s); routing through the restart budget",
                        r.index, code, killed)
        elif code == 0:
            r.done = True
            log.info("replica %d drained and exited 0", r.index)
            return
        elif code == DEGRADED_EXIT_CODE:
            r.degraded = True
            log.warning("replica %d ended DEGRADED (exit 17)", r.index)
            return
        # a crash.  Stable-for-a-window replicas restart their
        # backoff ladder from the bottom (the budget itself refills on
        # the same window rule inside RetryBudget.consume)
        if self._clock() - r.spawned_at > self.retry_window_s:
            r.consecutive_failures = 0
        r.consecutive_failures += 1
        if not r.budget.consume():
            self._degrade(r, code, cls)
        with self._fleet_lock:
            self.restarts_total += 1
        self._m_restarts.inc()
        delay = min(self.backoff_max_s,
                    self.backoff_base_s
                    * (2 ** (r.consecutive_failures - 1)))
        r.next_spawn_at = self._clock() + delay
        log.warning("replica %d died (%s); restart %d scheduled in "
                    "%.2fs (%d budget left)", r.index, cls,
                    r.incarnation, delay, r.budget.remaining)

    def _degrade(self, r: _Replica, code: int, cls: str) -> None:
        """Budget exhausted: end the fleet structured — the serving
        twin of training's checkpoint-and-queue degraded record."""
        # mark the replica BEFORE raising: with run_background() the
        # DegradedTraining dies with the daemon thread, and summary()
        # must still show which replica took the fleet down
        r.degraded = True
        r.last_exit = code
        with self._fleet_lock:
            restarts = self.restarts_total
        record = {
            "status": "degraded",
            "component": "serving",
            "reason": (f"replica {r.index} exhausted its restart "
                       f"budget ({self.retry_times} failures within "
                       f"{self.retry_window_s:.0f}s)"),
            "replica": r.index,
            "exit_code": code,
            "classification": cls,
            "incarnations": r.incarnation,
            "restarts_total": restarts,
            "replicas": self.replicas,
        }
        if self.run_dir:
            path = os.path.join(self.run_dir, "degraded.json")
            try:
                atomic_write_text(path, json.dumps(record, indent=2))
            except OSError:
                log.exception("could not mirror degraded record to %s",
                              path)
        self._flightrec.record(
            "fleet.degraded", replica=r.index, exit=code,
            classification=cls, restarts_total=restarts)
        self._persist_state()
        raise DegradedTraining(record["reason"], result=record)

    # ------------------------------------------------------------ autoscale
    def _fleet(self) -> List[_Replica]:
        """Locked copy of the replica slots — iterate the copy so the
        lock is never held across a spawn or a health probe."""
        with self._fleet_lock:
            return list(self._replicas)

    def _fleet_size(self) -> int:
        """The live fleet: slots that are neither finished nor on
        their way out (a retiring replica still drains, but traffic
        planning must not count it)."""
        return sum(1 for r in self._fleet()
                   if not r.done and not r.degraded and not r.retiring)

    def _record_fleet_size(self, reason: str) -> None:
        size = self._fleet_size()
        self._m_fleet.set(size)
        with self._fleet_lock:
            changed = (not self.replica_trajectory
                       or self.replica_trajectory[-1][1] != size)
            if changed:
                self.replica_trajectory.append(
                    (time.time(), size, reason))
        if changed:
            self._persist_state()

    def _persist_state(self) -> None:
        """Mirror ``scale_events`` + ``replica_trajectory`` to the run
        dir AT DECISION TIME — ``summary()`` dies with the process, a
        crashed supervisor must still leave its decisions for
        ``zoo-doctor``."""
        if not self.run_dir:
            return
        with self._fleet_lock:
            doc = {
                "written_unix": time.time(),
                "replicas": self.replicas,
                "restarts_total": self.restarts_total,
                "scale_events": list(self.scale_events),
                "replica_trajectory": [
                    [t, size, reason]
                    for t, size, reason in self.replica_trajectory],
            }
        try:
            atomic_write_text(
                os.path.join(self.run_dir, "supervisor.json"),
                json.dumps(doc, indent=2, sort_keys=True))
        except OSError:
            log.exception("could not persist supervisor state")

    def _replica_gauges(self, r: _Replica) -> Dict:
        """One replica's ``/metrics.json`` snapshot sections (gauges +
        histograms); {} when unreachable — the autoscaler treats a
        silent replica as contributing no signal, and the health loop
        separately decides whether it is dead."""
        if r.port is None:
            return {}
        from urllib import request as urlrequest
        try:
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{r.port}/metrics.json",
                    timeout=1.0) as resp:
                return json.loads(resp.read().decode())
        except Exception:   # noqa: BLE001 — probe, not a failure
            return {}

    def _healthz_error_rate(self, r: _Replica) -> bool:
        """Whether this replica's /healthz 503s for ``error_rate`` —
        the one signal that must HOLD scale-up (more replicas on a
        poisoned stream amplify the poison)."""
        if r.port is None:
            return False
        from urllib import error as urlerror
        from urllib import request as urlrequest
        try:
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{r.port}/healthz",
                    timeout=1.0):
                return False
        except urlerror.HTTPError as e:
            try:
                reason = json.loads(e.read().decode()).get("reason")
            except Exception:   # noqa: BLE001
                reason = None
            finally:
                e.close()
            return reason == "error_rate"
        except (urlerror.URLError, OSError):
            return False

    def _collect_signals(self) -> Dict:
        """Fleet-wide scale signals from the replicas' own exported
        metrics: max queue depth (every replica sees the same shared
        stream, so max ≈ truth even mid-scrape), max batch fill, max
        p50 request latency.  The error-rate /healthz probe is NOT
        taken here — it only matters when a scale-up is about to
        fire, so ``_autoscale`` checks it lazily at that moment
        instead of paying a second per-replica round trip every
        interval."""
        queue = fill = p50_ms = 0.0
        saw_metrics = False
        for r in self._fleet():
            if r.proc is None or r.done or r.degraded or r.retiring:
                continue
            snap = self._replica_gauges(r)
            if not snap:
                continue
            saw_metrics = True
            gauges = snap.get("gauges", {})
            queue = max(queue,
                        float(gauges.get("serving_queue_depth", 0.0)))
            fill = max(fill, float(gauges.get(
                "serving_batch_fill_ratio", 0.0)))
            hist = snap.get("histograms", {}).get(
                "serving_request_latency_seconds")
            if hist:
                p50_ms = max(p50_ms,
                             float(hist.get("p50") or 0.0) * 1000.0)
        return {"queue": queue, "fill": fill, "p50_ms": p50_ms,
                "saw_metrics": saw_metrics}

    def _error_rate_hold(self) -> bool:
        """Lazy scale-up gate: does ANY live replica 503 for
        error_rate right now?  Only called when a scale-up is
        otherwise ready to fire."""
        return any(
            self._healthz_error_rate(r) for r in self._fleet()
            if r.proc is not None and not r.done and not r.degraded
            and not r.retiring)

    def _scale_down_allowed(self) -> bool:
        """Scale-down is only trusted when every live replica's last
        /healthz probe was a plain 200: a warming replica (503
        warming_up, queue gauge frozen at boot) or a breaker-open one
        (broker invisible) cannot vouch that the backlog is really
        empty — retiring capacity on their say-so is the cold-boot
        scale-to-floor failure mode."""
        live = [r for r in self._fleet()
                if r.proc is not None and not r.done
                and not r.degraded and not r.retiring]
        return bool(live) and all(r.last_health == "ok"
                                  for r in live)

    def _read_slo_signal(self) -> Dict:
        """The latest SLO verdict from the injected feed, or {} when
        no feed is wired / the feed raises (a broken SLO evaluator
        must never take down the autoscaler with it)."""
        if self._slo_signal is None:
            return {}
        try:
            return dict(self._slo_signal() or {})
        except Exception:   # noqa: BLE001 — advisory signal
            log.exception("autoscaler: slo_signal raised; ignoring")
            return {}

    def _autoscale(self, now: float) -> None:
        if not self.autoscale or self._stop.is_set():
            return
        with self._fleet_lock:
            if now - self._last_autoscale_poll \
                    < self.autoscale_interval_s:
                return
            self._last_autoscale_poll = now
        sig = self._collect_signals()
        if not sig["saw_metrics"]:
            # nobody reachable yet (cold fleet / every port pending):
            # no evidence, no decision — hysteresis clocks reset so a
            # blind window can never accumulate into a scale event
            self._pressure_since = self._idle_since = None
            return
        slo = self._read_slo_signal()
        if slo:
            # ride the scale-event record so forensics can see WHICH
            # signal fired each decision
            sig["slo_alert"] = str(slo.get("alert", "ok"))
            if slo.get("budget_remaining") is not None:
                sig["slo_budget_remaining"] = float(
                    slo["budget_remaining"])
        slo_page = sig.get("slo_alert") == "page"
        pressure = slo_page \
            or sig["queue"] > self.scale_up_queue_depth or (
                self.scale_up_latency_p50_ms > 0
                and sig["p50_ms"] > self.scale_up_latency_p50_ms)
        # idle keys on the live backlog alone: the fill gauge holds
        # the LAST batch's ratio, so a full final batch would read
        # stale-high forever and wedge scale-down.  Fill still rides
        # every scale event's signal record for operator forensics.
        idle = sig["queue"] <= 0
        self._pressure_since = (
            None if not pressure
            else self._pressure_since if self._pressure_since
            is not None else now)
        self._idle_since = (
            None if not idle
            else self._idle_since if self._idle_since
            is not None else now)
        in_cooldown = now - self._last_scale_at < self.scale_cooldown_s
        size = self._fleet_size()
        if pressure and size < self.max_replicas and not in_cooldown \
                and now - self._pressure_since \
                >= self.scale_up_sustain_s:
            if self._error_rate_hold():
                log.warning(
                    "autoscaler: scale-up held — a replica 503s "
                    "error_rate (queue=%.0f); more replicas would "
                    "amplify a poisoned stream", sig["queue"])
                return
            self._scale_up(now, sig)
        elif idle and size > self.min_replicas and not in_cooldown \
                and now - self._idle_since >= self.scale_down_idle_s \
                and self._scale_down_allowed():
            budget = sig.get("slo_budget_remaining")
            if budget is not None and budget <= 0:
                # error budget exhausted: the queue may be empty only
                # because users are being turned away — retiring
                # capacity now bakes the outage in.  Hold until the
                # budget recovers above zero.
                self._m_slo_hold.labels("scale_down").inc()
                log.warning(
                    "autoscaler: scale-down held — SLO error budget "
                    "exhausted (remaining=%.3f, alert=%s)", budget,
                    sig.get("slo_alert", "?"))
                return
            self._scale_down(now, sig)

    def _scale_up(self, now: float, sig: Dict) -> None:
        with self._fleet_lock:
            index = len(self._replicas)
            r = _Replica(index=index,
                         port_file=os.path.join(
                             self._state_dir,
                             f"replica-{index}.port"),
                         budget=RetryBudget(self.retry_times,
                                            self.retry_window_s))
            self._replicas.append(r)
        self._spawn(r)
        self._last_scale_at = now
        self._pressure_since = None
        self._m_scale.labels("up").inc()
        with self._fleet_lock:
            self.scale_events.append({
                "direction": "up", "replica": index,
                "fleet": self._fleet_size(), "signals": sig})
        self._flightrec.record(
            "scale.up", replica=index, fleet=self._fleet_size(),
            signals=sig)
        self._record_fleet_size("scale_up")
        self._persist_state()
        log.warning(
            "autoscaler: scale UP → replica %d spawned (fleet %d, "
            "queue=%.0f, p50=%.0fms)", index, self._fleet_size(),
            sig["queue"], sig["p50_ms"])

    def _scale_down(self, now: float, sig: Dict) -> None:
        """Retire the highest-index live replica via the SIGTERM
        drain contract: it finishes + acks in-flight batches, flushes
        metrics, and exits 0 — and is never restarted."""
        victim = None
        for r in reversed(self._fleet()):
            if r.proc is not None and r.proc.poll() is None \
                    and not r.retiring and not r.done \
                    and not r.degraded:
                victim = r
                break
        if victim is None:
            return
        victim.retiring = True
        # a retiring replica leaves the health/heartbeat watchdog, so
        # it needs its own wedge guard: past the drain window it is
        # SIGKILLed by _tick (same escalation drain_fleet applies)
        victim.retire_deadline = now + self.drain_timeout_s
        victim.proc.terminate()
        self._last_scale_at = now
        self._idle_since = None
        self._m_scale.labels("down").inc()
        with self._fleet_lock:
            self.scale_events.append({
                "direction": "down", "replica": victim.index,
                "fleet": self._fleet_size(), "signals": sig})
        self._flightrec.record(
            "scale.down", replica=victim.index,
            fleet=self._fleet_size(), signals=sig)
        self._record_fleet_size("scale_down")
        self._persist_state()
        log.warning(
            "autoscaler: scale DOWN → replica %d draining (fleet %d, "
            "idle %.1fs)", victim.index, self._fleet_size(),
            self.scale_down_idle_s)

    # ---------------------------------------------------------- health
    def _probe(self, r: _Replica) -> str:
        """One /healthz probe: ``ok`` | ``not_ready`` (503 — alive) |
        ``unreachable`` | ``no_port`` (not yet published)."""
        if r.port is None:
            try:
                with open(r.port_file) as f:
                    r.port = int(f.read().strip() or 0) or None
            except (OSError, ValueError):
                r.port = None
        if r.port is None:
            return "no_port"
        from urllib import error as urlerror
        from urllib import request as urlrequest
        try:
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{r.port}/healthz",
                    timeout=1.0):
                return "ok"
        except urlerror.HTTPError as e:
            e.close()
            return "not_ready"     # 503: alive, deliberately not-ready
        except (urlerror.URLError, OSError):
            return "unreachable"

    def _poll_health(self, r: _Replica, now: float) -> None:
        if now - r.last_health_at < self.health_interval_s:
            return
        r.last_health_at = now
        status = self._probe(r)
        r.last_health = status
        if status in ("ok", "not_ready"):
            r.health_fails = 0
        elif status == "unreachable":
            r.health_fails += 1
            if r.health_fails >= self.health_fail_threshold:
                self._kill_replica(
                    r, f"/healthz unreachable x{r.health_fails}")
                return
        elif status == "no_port" and \
                now - r.spawned_at > self.startup_grace_s:
            self._kill_replica(
                r, f"no /healthz port published within "
                   f"{self.startup_grace_s:.0f}s of spawn")
            return
        # heartbeat staleness: a process that polls as running but
        # whose serve loop is wedged (hung predict, dead collective)
        # stops beating — flag it before clients notice
        if self.run_dir:
            hb = read_heartbeats(self.run_dir).get(r.index)
            if hb is not None and \
                    time.time() - float(hb.get("time", 0.0)) \
                    > self.heartbeat_timeout_s and \
                    now - r.spawned_at > self.heartbeat_timeout_s:
                self._kill_replica(
                    r, f"heartbeat stale > "
                       f"{self.heartbeat_timeout_s:.0f}s")

    def _kill_replica(self, r: _Replica, reason: str) -> None:
        """TERM→KILL a wedged replica; the next tick classifies its
        exit and routes it through the normal restart budget."""
        proc = r.proc
        if proc is None or proc.poll() is not None:
            return
        log.error("killing replica %d (pid %d): %s", r.index,
                  proc.pid, reason)
        r.kill_reason = reason
        self._flightrec.record(
            "replica.kill", replica=r.index, reason=reason)
        proc.terminate()
        try:
            proc.wait(2.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(2.0)
            except subprocess.TimeoutExpired:   # pragma: no cover
                log.error("replica %d survived SIGKILL", r.index)

    # ------------------------------------------------------------- run loop
    def _tick(self) -> None:
        now = self._clock()
        alive = 0
        for r in self._fleet():
            if r.proc is None:
                if (not r.done and not r.degraded
                        and r.next_spawn_at is not None
                        and now >= r.next_spawn_at):
                    self._spawn(r)
                    alive += 1
                continue
            code = r.proc.poll()
            if code is None:
                alive += 1
                if r.retiring:
                    if now >= r.retire_deadline:
                        log.warning(
                            "replica %d ignored SIGTERM for %.0fs "
                            "during scale-down; escalating to "
                            "SIGKILL", r.index, self.drain_timeout_s)
                        r.proc.kill()
                        r.retire_deadline = now + 2.0   # reap window
                else:
                    self._poll_health(r, now)
            else:
                self._handle_exit(r, code)
        self._m_running.set(alive)
        self._autoscale(now)
        self._record_fleet_size("tick")

    def run(self, poll_interval_s: float = 0.25) -> Dict:
        """Supervise until drained; returns the fleet summary.  Raises
        :class:`DegradedTraining` on budget exhaustion (wrap the CLI
        in ``degraded_exit()`` for the exit-17 protocol)."""
        self.install_signal_handlers()
        for r in self._fleet():
            self._spawn(r)
        try:
            while not self._stop.is_set():
                self._tick()
                if all(r.done or r.degraded for r in self._fleet()):
                    break
                time.sleep(poll_interval_s)
        finally:
            self.drain_fleet()
            self._m_running.set(0)
        return self.summary()

    def run_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True,
                             name="zoo-serving-supervisor")
        t.start()
        return t

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Block until every live replica answers /healthz 200 (for
        tests and scripted bring-up)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            live = [r for r in self._fleet()
                    if not r.done and not r.degraded]
            if live and all(self._probe(r) == "ok" for r in live):
                return True
            time.sleep(0.1)
        return False

    def drain_fleet(self) -> Dict[int, Optional[int]]:
        """Graceful fleet drain: SIGTERM each replica (its handler
        finishes + acks in-flight batches and exits 0), escalate to
        SIGKILL per process past ``drain_timeout_s``, reap everything.
        Returns {replica_index: exit code}."""
        live = [r for r in self._fleet()
                if r.proc is not None and r.proc.poll() is None]
        for r in live:
            r.proc.terminate()
        deadline = time.monotonic() + self.drain_timeout_s
        codes: Dict[int, Optional[int]] = {}
        for r in live:
            try:
                code = r.proc.wait(
                    max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                log.warning("replica %d ignored SIGTERM for %.0fs; "
                            "escalating to SIGKILL", r.index,
                            self.drain_timeout_s)
                r.proc.kill()
                try:
                    code = r.proc.wait(2.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    code = None
            codes[r.index] = code
            r.last_exit = code
            if code == 0:
                r.done = True
            r.proc = None
        return codes

    def summary(self) -> Dict:
        with self._fleet_lock:
            rs = list(self._replicas)
            out = {
                "replicas": self.replicas,
                "restarts_total": self.restarts_total,
                "done": [r.index for r in rs if r.done],
                "degraded": [r.index for r in rs if r.degraded],
                "exit_codes": {r.index: r.last_exit for r in rs},
            }
            if self.autoscale:
                out["min_replicas"] = self.min_replicas
                out["max_replicas"] = self.max_replicas
                out["scale_events"] = list(self.scale_events)
                out["replica_trajectory"] = [
                    size for _t, size, _r in self.replica_trajectory]
        return out


def supervisor_main(argv=None) -> int:
    """``python -m analytics_zoo_tpu.serving.supervisor``: run a
    replica fleet from config.yaml (``params.replicas`` /
    ``params.consumer_group``), speaking the launcher degraded-exit
    protocol on budget exhaustion."""
    p = argparse.ArgumentParser(prog="zoo-serving-supervisor")
    p.add_argument("--config", "-c", default="config.yaml")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica count (default config "
                        "params.replicas, else 3)")
    p.add_argument("--consumer-group", default=None,
                   help="shared consumer group (default config "
                        "params.consumer_group, else 'serving')")
    p.add_argument("--run-dir", default=None,
                   help="fleet state dir: per-replica heartbeat "
                        "slots + degraded.json")
    p.add_argument("--retry-times", type=int, default=None)
    p.add_argument("--retry-window-s", type=float, default=None)
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    p.add_argument("--min-replicas", type=int, default=None,
                   help="autoscaler floor (default config "
                        "params.min_replicas; equal to --replicas "
                        "disables autoscaling)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler ceiling (default config "
                        "params.max_replicas)")
    args = p.parse_args(argv)

    from analytics_zoo_tpu.serving.server import ServingConfig
    cfg = (ServingConfig.from_yaml(args.config)
           if os.path.exists(args.config) else ServingConfig())

    def _cfg_int(key):
        v = cfg.extra.get(key)
        return int(v) if v not in (None, "") else None

    replicas = args.replicas
    if replicas is None:
        replicas = _cfg_int("params.replicas") or 3
    min_replicas = (args.min_replicas
                    if args.min_replicas is not None
                    else _cfg_int("params.min_replicas"))
    max_replicas = (args.max_replicas
                    if args.max_replicas is not None
                    else _cfg_int("params.max_replicas"))
    group = (args.consumer_group or cfg.consumer_group or "serving")
    sup = ServingSupervisor(
        cli_worker_factory(args.config, consumer_group=group),
        replicas=replicas,
        retry_times=args.retry_times,
        retry_window_s=args.retry_window_s,
        run_dir=args.run_dir,
        drain_timeout_s=args.drain_timeout_s,
        min_replicas=min_replicas,
        max_replicas=max_replicas)
    with degraded_exit():
        summary = sup.run()
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(supervisor_main())
