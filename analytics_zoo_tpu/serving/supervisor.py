"""Serving replica supervisor — the resilience layer of the fleet.

The reference's Cluster Serving got multi-replica fault tolerance for
free from Spark executor restart + Redis consumer groups (BigDL,
arXiv 1804.05839 §serving; BigDL 2.0, arXiv 2204.01715).  Our rebuild
provides — and beats — that property itself: a
:class:`ServingSupervisor` spawns N ``ClusterServing`` worker
processes against ONE consumer group (distinct consumer names, so each
record is delivered to exactly one replica and a dead replica's
un-acked records are PEL-reclaimed by its peers), watches them via

* **process exit** — classified with
  :func:`~analytics_zoo_tpu.resilience.detector.classify_exit`
  (``ok`` / ``error(N)`` / ``signal(SIGKILL)`` …),
* **/healthz** — each replica publishes its metrics port through
  ``ZOO_TPU_SERVING_PORT_FILE``; 200/503 both mean *alive* (503 =
  not-ready, e.g. ``breaker_open`` during a broker outage — restarting
  cannot fix that, so the supervisor deliberately does NOT),
* **heartbeats** — with a ``run_dir``, replicas write the PR 6
  ``host-<k>/heartbeat.json``; a staleness past
  ``resilience.heartbeat_timeout_s`` flags a replica whose process
  still polls as running but whose serve loop is wedged,

and restarts crashed replicas with exponential backoff under a
:class:`~analytics_zoo_tpu.resilience.policy.RetryBudget` (the
reference's time-windowed budget).  Budget exhausted → the fleet ends
*structured*: a ``DegradedTraining``-style record (mirrored to
``<run_dir>/degraded.json``) and
:data:`~analytics_zoo_tpu.resilience.policy.DEGRADED_EXIT_CODE` (17)
from the CLI — honoring the ``zoo-launch --max-degraded`` contract, so
an orchestrator can tell "serving tier gave up in an orderly way"
from "supervisor crashed".

SIGTERM to the supervisor drains the fleet gracefully: each replica
gets SIGTERM, finishes + acks its in-flight batches, flushes metrics,
and exits 0 (escalating to SIGKILL only past ``drain_timeout_s``).

The supervisor process never touches a device — replicas are separate
processes, so the fleet controller can run on a host with no
accelerator access at all.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.fsutil import atomic_write_text
from analytics_zoo_tpu.observability import get_registry
from analytics_zoo_tpu.resilience.detector import (
    classify_exit, read_heartbeats)
from analytics_zoo_tpu.resilience.policy import (
    DEGRADED_EXIT_CODE, DegradedTraining, RetryBudget, degraded_exit)

log = logging.getLogger("analytics_zoo_tpu.serving.supervisor")

#: env var through which a replica publishes its bound /metrics
#: (+/healthz) port back to the supervisor (server._publish_port)
ENV_PORT_FILE = "ZOO_TPU_SERVING_PORT_FILE"

#: worker_factory signature: (replica_index, incarnation) ->
#: (argv list, extra env dict) — incarnation 0 is the first spawn,
#: so tests can arm chaos for exactly one life of a replica
WorkerFactory = Callable[[int, int], Tuple[List[str], Dict[str, str]]]


def _set_pdeathsig():   # pragma: no cover — linux only
    """Replica dies with the supervisor (launcher._set_pdeathsig's
    role, re-implemented here so this module never imports the
    jax-adjacent parallel package)."""
    try:
        import ctypes
        import signal
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:   # noqa: BLE001 — non-linux
        pass


def cli_worker_factory(config_path: str,
                       consumer_group: str = "serving",
                       extra_args: Tuple[str, ...] = (),
                       python: str = sys.executable) -> WorkerFactory:
    """The default replica command: ``zoo-serving start`` against one
    shared consumer group, a unique consumer name per replica slot,
    and an ephemeral metrics port (the replica publishes the bound
    port back through :data:`ENV_PORT_FILE`)."""
    def factory(index: int, incarnation: int):
        cmd = [python, "-m", "analytics_zoo_tpu.serving.cli", "start",
               "-c", config_path,
               "--consumer-group", consumer_group,
               "--consumer-name", f"replica-{index}",
               "--metrics-port", "0", *extra_args]
        return cmd, {}
    return factory


@dataclasses.dataclass
class _Replica:
    """Supervisor-side state of one replica slot."""
    index: int
    port_file: str
    budget: RetryBudget
    proc: Optional[subprocess.Popen] = None
    incarnation: int = 0          # lives spawned so far
    port: Optional[int] = None    # discovered /healthz port
    spawned_at: float = 0.0
    next_spawn_at: Optional[float] = None   # backoff restart schedule
    consecutive_failures: int = 0
    health_fails: int = 0         # consecutive unreachable probes
    last_health_at: float = 0.0
    last_exit: Optional[int] = None
    done: bool = False            # exited 0 (orderly drain)
    degraded: bool = False        # exited DEGRADED_EXIT_CODE
    kill_reason: Optional[str] = None   # supervisor-initiated kill


class ServingSupervisor:
    """Spawn, watch, restart, and drain a fleet of serving replicas.

    ``run()`` blocks until the fleet drains (``stop()`` / SIGTERM /
    every replica exiting 0 or 17) and returns a summary dict — or
    raises :class:`DegradedTraining` when a replica exhausts its
    restart budget (the CLI maps that to exit 17 via
    ``degraded_exit``)."""

    def __init__(self, worker_factory: WorkerFactory,
                 replicas: int = 3, *,
                 retry_times: Optional[int] = None,
                 retry_window_s: Optional[float] = None,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 10.0,
                 health_interval_s: float = 2.0,
                 health_fail_threshold: int = 3,
                 startup_grace_s: float = 30.0,
                 heartbeat_timeout_s: Optional[float] = None,
                 run_dir: Optional[str] = None,
                 drain_timeout_s: float = 30.0):
        if retry_times is None:
            retry_times = int(get_config().get(
                "serving.supervisor_retry_times", 5))
        if retry_window_s is None:
            retry_window_s = float(get_config().get(
                "serving.supervisor_retry_window_s", 60.0))
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(get_config().get(
                "resilience.heartbeat_timeout_s", 30.0))
        self.worker_factory = worker_factory
        self.replicas = int(replicas)
        self.retry_times = int(retry_times)
        self.retry_window_s = float(retry_window_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.health_interval_s = float(health_interval_s)
        self.health_fail_threshold = max(int(health_fail_threshold), 1)
        self.startup_grace_s = float(startup_grace_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.run_dir = run_dir
        self._state_dir = run_dir or tempfile.mkdtemp(
            prefix="zoo-serving-supervisor-")
        os.makedirs(self._state_dir, exist_ok=True)
        self._replicas: List[_Replica] = [
            _Replica(index=i,
                     port_file=os.path.join(self._state_dir,
                                            f"replica-{i}.port"),
                     budget=RetryBudget(self.retry_times,
                                        self.retry_window_s))
            for i in range(self.replicas)]
        self.restarts_total = 0
        self._stop = threading.Event()
        reg = get_registry()
        self._m_running = reg.gauge(
            "serving_replicas_running",
            "serving replicas currently alive under the supervisor")
        self._m_restarts = reg.counter(
            "serving_replica_restarts_total",
            "replica restarts performed by the supervisor")
        self._m_exits = reg.counter(
            "serving_replica_exits_total",
            "replica exits observed, by classified exit code",
            labels=("class",))

    # -------------------------------------------------------------- control
    def stop(self) -> None:
        """Request a graceful fleet drain (also the SIGTERM handler)."""
        self._stop.set()

    def install_signal_handlers(self) -> bool:
        """SIGTERM → ``stop()`` (graceful drain).  Main-thread only;
        returns False when not installable."""
        import signal
        try:
            signal.signal(signal.SIGTERM,
                          lambda _sig, _frame: self.stop())
            return True
        except ValueError:
            return False

    # ------------------------------------------------------------ lifecycle
    def _spawn(self, r: _Replica) -> None:
        cmd, env = self.worker_factory(r.index, r.incarnation)
        full = dict(os.environ)
        full.update(env or {})
        try:
            os.remove(r.port_file)     # stale port from a past life
        except OSError:
            pass
        if self.run_dir:
            # drop the dead incarnation's heartbeat too (the same
            # contamination guard the launcher applies to reused run
            # dirs): the replacement's first beat only lands after
            # model load, and judging it by its predecessor's stale
            # timestamp would kill every slow-starting respawn until
            # the budget spuriously degrades the fleet
            try:
                os.remove(os.path.join(self.run_dir,
                                       f"host-{r.index}",
                                       "heartbeat.json"))
            except OSError:
                pass
        r.port = None
        r.health_fails = 0
        r.kill_reason = None
        full[ENV_PORT_FILE] = r.port_file
        # chaos process filtering + const metric labels both key on
        # the replica slot; a test factory may override
        full.setdefault("ZOO_TPU_PROCESS_ID", str(r.index))
        if self.run_dir:
            slot = os.path.join(self.run_dir, f"host-{r.index}")
            os.makedirs(slot, exist_ok=True)
            full.setdefault("ZOO_TPU_METRICS_DIR", slot)
        r.proc = subprocess.Popen(cmd, env=full,
                                  preexec_fn=_set_pdeathsig)
        r.incarnation += 1
        r.spawned_at = time.monotonic()
        r.next_spawn_at = None
        log.info("replica %d spawned (incarnation %d, pid %d)",
                 r.index, r.incarnation, r.proc.pid)

    def _handle_exit(self, r: _Replica, code: int) -> None:
        r.proc = None
        r.last_exit = code
        killed, r.kill_reason = r.kill_reason, None
        cls = ("killed_by_supervisor" if killed
               else "degraded" if code == DEGRADED_EXIT_CODE
               else classify_exit(code))
        self._m_exits.labels(cls).inc()
        # a supervisor-initiated kill (wedged heartbeat, unreachable
        # /healthz) must be restarted no matter HOW the replica ended:
        # its SIGTERM handler drains gracefully to exit 0, and taking
        # that as an orderly retirement would silently shrink the
        # fleet with no restart and no degraded record
        if killed:
            log.warning("replica %d exited %d after supervisor kill "
                        "(%s); routing through the restart budget",
                        r.index, code, killed)
        elif code == 0:
            r.done = True
            log.info("replica %d drained and exited 0", r.index)
            return
        elif code == DEGRADED_EXIT_CODE:
            r.degraded = True
            log.warning("replica %d ended DEGRADED (exit 17)", r.index)
            return
        # a crash.  Stable-for-a-window replicas restart their
        # backoff ladder from the bottom (the budget itself refills on
        # the same window rule inside RetryBudget.consume)
        if time.monotonic() - r.spawned_at > self.retry_window_s:
            r.consecutive_failures = 0
        r.consecutive_failures += 1
        if not r.budget.consume():
            self._degrade(r, code, cls)
        self.restarts_total += 1
        self._m_restarts.inc()
        delay = min(self.backoff_max_s,
                    self.backoff_base_s
                    * (2 ** (r.consecutive_failures - 1)))
        r.next_spawn_at = time.monotonic() + delay
        log.warning("replica %d died (%s); restart %d scheduled in "
                    "%.2fs (%d budget left)", r.index, cls,
                    r.incarnation, delay, r.budget.remaining)

    def _degrade(self, r: _Replica, code: int, cls: str) -> None:
        """Budget exhausted: end the fleet structured — the serving
        twin of training's checkpoint-and-queue degraded record."""
        # mark the replica BEFORE raising: with run_background() the
        # DegradedTraining dies with the daemon thread, and summary()
        # must still show which replica took the fleet down
        r.degraded = True
        r.last_exit = code
        record = {
            "status": "degraded",
            "component": "serving",
            "reason": (f"replica {r.index} exhausted its restart "
                       f"budget ({self.retry_times} failures within "
                       f"{self.retry_window_s:.0f}s)"),
            "replica": r.index,
            "exit_code": code,
            "classification": cls,
            "incarnations": r.incarnation,
            "restarts_total": self.restarts_total,
            "replicas": self.replicas,
        }
        if self.run_dir:
            path = os.path.join(self.run_dir, "degraded.json")
            try:
                atomic_write_text(path, json.dumps(record, indent=2))
            except OSError:
                log.exception("could not mirror degraded record to %s",
                              path)
        raise DegradedTraining(record["reason"], result=record)

    # ---------------------------------------------------------- health
    def _probe(self, r: _Replica) -> str:
        """One /healthz probe: ``ok`` | ``not_ready`` (503 — alive) |
        ``unreachable`` | ``no_port`` (not yet published)."""
        if r.port is None:
            try:
                with open(r.port_file) as f:
                    r.port = int(f.read().strip() or 0) or None
            except (OSError, ValueError):
                r.port = None
        if r.port is None:
            return "no_port"
        from urllib import error as urlerror
        from urllib import request as urlrequest
        try:
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{r.port}/healthz",
                    timeout=1.0):
                return "ok"
        except urlerror.HTTPError as e:
            e.close()
            return "not_ready"     # 503: alive, deliberately not-ready
        except (urlerror.URLError, OSError):
            return "unreachable"

    def _poll_health(self, r: _Replica, now: float) -> None:
        if now - r.last_health_at < self.health_interval_s:
            return
        r.last_health_at = now
        status = self._probe(r)
        if status in ("ok", "not_ready"):
            r.health_fails = 0
        elif status == "unreachable":
            r.health_fails += 1
            if r.health_fails >= self.health_fail_threshold:
                self._kill_replica(
                    r, f"/healthz unreachable x{r.health_fails}")
                return
        elif status == "no_port" and \
                now - r.spawned_at > self.startup_grace_s:
            self._kill_replica(
                r, f"no /healthz port published within "
                   f"{self.startup_grace_s:.0f}s of spawn")
            return
        # heartbeat staleness: a process that polls as running but
        # whose serve loop is wedged (hung predict, dead collective)
        # stops beating — flag it before clients notice
        if self.run_dir:
            hb = read_heartbeats(self.run_dir).get(r.index)
            if hb is not None and \
                    time.time() - float(hb.get("time", 0.0)) \
                    > self.heartbeat_timeout_s and \
                    now - r.spawned_at > self.heartbeat_timeout_s:
                self._kill_replica(
                    r, f"heartbeat stale > "
                       f"{self.heartbeat_timeout_s:.0f}s")

    def _kill_replica(self, r: _Replica, reason: str) -> None:
        """TERM→KILL a wedged replica; the next tick classifies its
        exit and routes it through the normal restart budget."""
        proc = r.proc
        if proc is None or proc.poll() is not None:
            return
        log.error("killing replica %d (pid %d): %s", r.index,
                  proc.pid, reason)
        r.kill_reason = reason
        proc.terminate()
        try:
            proc.wait(2.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(2.0)
            except subprocess.TimeoutExpired:   # pragma: no cover
                log.error("replica %d survived SIGKILL", r.index)

    # ------------------------------------------------------------- run loop
    def _tick(self) -> None:
        now = time.monotonic()
        alive = 0
        for r in self._replicas:
            if r.proc is None:
                if (not r.done and not r.degraded
                        and r.next_spawn_at is not None
                        and now >= r.next_spawn_at):
                    self._spawn(r)
                    alive += 1
                continue
            code = r.proc.poll()
            if code is None:
                alive += 1
                self._poll_health(r, now)
            else:
                self._handle_exit(r, code)
        self._m_running.set(alive)

    def run(self, poll_interval_s: float = 0.25) -> Dict:
        """Supervise until drained; returns the fleet summary.  Raises
        :class:`DegradedTraining` on budget exhaustion (wrap the CLI
        in ``degraded_exit()`` for the exit-17 protocol)."""
        self.install_signal_handlers()
        for r in self._replicas:
            self._spawn(r)
        try:
            while not self._stop.is_set():
                self._tick()
                if all(r.done or r.degraded for r in self._replicas):
                    break
                time.sleep(poll_interval_s)
        finally:
            self.drain_fleet()
            self._m_running.set(0)
        return self.summary()

    def run_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True,
                             name="zoo-serving-supervisor")
        t.start()
        return t

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Block until every live replica answers /healthz 200 (for
        tests and scripted bring-up)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            live = [r for r in self._replicas
                    if not r.done and not r.degraded]
            if live and all(self._probe(r) == "ok" for r in live):
                return True
            time.sleep(0.1)
        return False

    def drain_fleet(self) -> Dict[int, Optional[int]]:
        """Graceful fleet drain: SIGTERM each replica (its handler
        finishes + acks in-flight batches and exits 0), escalate to
        SIGKILL per process past ``drain_timeout_s``, reap everything.
        Returns {replica_index: exit code}."""
        live = [r for r in self._replicas
                if r.proc is not None and r.proc.poll() is None]
        for r in live:
            r.proc.terminate()
        deadline = time.monotonic() + self.drain_timeout_s
        codes: Dict[int, Optional[int]] = {}
        for r in live:
            try:
                code = r.proc.wait(
                    max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                log.warning("replica %d ignored SIGTERM for %.0fs; "
                            "escalating to SIGKILL", r.index,
                            self.drain_timeout_s)
                r.proc.kill()
                try:
                    code = r.proc.wait(2.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    code = None
            codes[r.index] = code
            r.last_exit = code
            if code == 0:
                r.done = True
            r.proc = None
        return codes

    def summary(self) -> Dict:
        return {
            "replicas": self.replicas,
            "restarts_total": self.restarts_total,
            "done": [r.index for r in self._replicas if r.done],
            "degraded": [r.index for r in self._replicas
                         if r.degraded],
            "exit_codes": {r.index: r.last_exit
                           for r in self._replicas},
        }


def supervisor_main(argv=None) -> int:
    """``python -m analytics_zoo_tpu.serving.supervisor``: run a
    replica fleet from config.yaml (``params.replicas`` /
    ``params.consumer_group``), speaking the launcher degraded-exit
    protocol on budget exhaustion."""
    p = argparse.ArgumentParser(prog="zoo-serving-supervisor")
    p.add_argument("--config", "-c", default="config.yaml")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica count (default config "
                        "params.replicas, else 3)")
    p.add_argument("--consumer-group", default=None,
                   help="shared consumer group (default config "
                        "params.consumer_group, else 'serving')")
    p.add_argument("--run-dir", default=None,
                   help="fleet state dir: per-replica heartbeat "
                        "slots + degraded.json")
    p.add_argument("--retry-times", type=int, default=None)
    p.add_argument("--retry-window-s", type=float, default=None)
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    args = p.parse_args(argv)

    from analytics_zoo_tpu.serving.server import ServingConfig
    cfg = (ServingConfig.from_yaml(args.config)
           if os.path.exists(args.config) else ServingConfig())
    replicas = args.replicas
    if replicas is None:
        replicas = int(cfg.extra.get("params.replicas") or 3)
    group = (args.consumer_group or cfg.consumer_group or "serving")
    sup = ServingSupervisor(
        cli_worker_factory(args.config, consumer_group=group),
        replicas=replicas,
        retry_times=args.retry_times,
        retry_window_s=args.retry_window_s,
        run_dir=args.run_dir,
        drain_timeout_s=args.drain_timeout_s)
    with degraded_exit():
        summary = sup.run()
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(supervisor_main())
