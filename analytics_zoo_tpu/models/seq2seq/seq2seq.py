"""Seq2seq: RNN encoder/decoder with a state bridge and greedy infer.

Reference: zoo/models/seq2seq/Seq2seq.scala:50, RNNEncoder/RNNDecoder,
Bridge.scala:156 ("pass" forwards encoder states; "dense" maps them
through a learned projection), and the token-by-token ``infer`` loop.

TPU design: teacher-forced training runs both stacks as lax.scans in a
single XLA program; greedy decoding is ALSO one program — a lax.scan
over decode steps feeding the argmax back, instead of the reference's
per-token forward calls from the driver.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    Params, State, fold_name,
)
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Embedding
from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import LSTM
from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet


class Seq2seq(KerasNet):
    """Token seq2seq over a shared vocab (chatbot example workload)."""

    def __init__(self, vocab_size: int, embed_dim: int = 128,
                 hidden_sizes: Sequence[int] = (128,),
                 bridge: str = "pass", name: Optional[str] = None):
        super().__init__(name=name)
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.hidden_sizes = list(hidden_sizes)
        assert bridge in ("pass", "dense")
        if bridge == "pass":
            # encoder carry feeds the decoder unchanged: sizes must align
            assert len(set(self.hidden_sizes)) == 1
        self.bridge = bridge

        self.embedding = Embedding(self.vocab_size, self.embed_dim,
                                   init="uniform")
        self.encoder_rnns = [LSTM(h, return_sequences=True)
                             for h in self.hidden_sizes]
        self.decoder_rnns = [LSTM(h, return_sequences=True)
                             for h in self.hidden_sizes]
        self.bridge_layers = (
            [Dense(2 * h) for h in self.hidden_sizes]
            if bridge == "dense" else [])
        self.generator = Dense(self.vocab_size)
        self.layers = ([self.embedding] + self.encoder_rnns +
                       self.decoder_rnns + self.bridge_layers +
                       [self.generator])
        self.batch_input_shape = [(None, None), (None, None)]

    # ------------------------------------------------------------ building
    def build(self, rng, input_shape) -> Params:
        params: Params = {}
        params[self.embedding.name] = self.embedding.init(
            fold_name(rng, self.embedding.name), (None, 1))["params"]
        shape = (None, None, self.embed_dim)
        for enc, dec in zip(self.encoder_rnns, self.decoder_rnns):
            params[enc.name] = enc.init(
                fold_name(rng, enc.name), shape)["params"]
            params[dec.name] = dec.init(
                fold_name(rng, dec.name), shape)["params"]
            shape = (None, None, enc.output_dim)
        for i, bl in enumerate(self.bridge_layers):
            h = self.hidden_sizes[i]
            params[bl.name] = bl.init(
                fold_name(rng, bl.name), (None, 2 * h))["params"]
        params[self.generator.name] = self.generator.init(
            fold_name(rng, self.generator.name),
            (None, self.hidden_sizes[-1]))["params"]
        return params

    def init_state(self, input_shape) -> State:
        return {}

    def compute_output_shape(self, input_shape):
        dec_shape = input_shape[1]
        return (dec_shape[0], dec_shape[1], self.vocab_size)

    # ------------------------------------------------------------- forward
    def _encode(self, params, enc_ids):
        x = self.embedding.call(params[self.embedding.name], enc_ids)
        carries = []
        for enc in self.encoder_rnns:
            x, carry = enc.run(params[enc.name], x)
            carries.append(carry)
        return carries

    def _bridge(self, params, carries):
        if self.bridge == "pass":
            return carries
        out = []
        for bl, (h, c) in zip(self.bridge_layers, carries):
            joined = jnp.concatenate([h, c], axis=-1)
            mapped = bl.call(params[bl.name], joined)
            nh, nc = jnp.split(mapped, 2, axis=-1)
            out.append((nh, nc))
        return out

    def apply(self, params, inputs, state=None, training=False, rng=None):
        enc_ids, dec_ids = inputs
        carries = self._bridge(params, self._encode(params, enc_ids))
        x = self.embedding.call(params[self.embedding.name], dec_ids)
        for dec, carry in zip(self.decoder_rnns, carries):
            x, _ = dec.run(params[dec.name], x, initial_carry=carry)
        logits = self.generator.call(params[self.generator.name], x)
        return logits, state

    # ------------------------------------------------- decode primitives
    # ``prefill`` and ``decode_step`` are the two pure programs the
    # whole generative story is built from: ``infer`` composes them
    # into one whole-sequence device loop, while the serving engine's
    # decode-step scheduler (serving/engine/decode.py) compiles
    # ``decode_step`` once per batch bucket and calls it once per
    # ITERATION — admitting and retiring sequences between calls.

    def prefill(self, params: Params, enc_ids):
        """Encode + bridge: the per-sequence decode state a new
        sequence enters the decode loop with.  ``enc_ids``
        (batch, enc_len) int32 → tuple of per-layer LSTM carries,
        each an ``(h, c)`` pair of (batch, hidden) arrays."""
        return tuple(self._bridge(params, self._encode(params, enc_ids)))

    def decode_step(self, params: Params, tok, carries):
        """One greedy decode iteration: last token (batch,) int32 +
        carries → (next token (batch,) int32, new carries)."""
        x = self.embedding.call(params[self.embedding.name],
                                tok[:, None])
        new_carries = []
        for dec, carry in zip(self.decoder_rnns, carries):
            x, nc = dec.run(params[dec.name], x, initial_carry=carry)
            new_carries.append(nc)
        logits = self.generator.call(params[self.generator.name],
                                     x[:, 0])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return nxt, tuple(new_carries)

    def initial_carries(self, batch: int):
        """Zero decode state shaped like one ``prefill`` row batch —
        the slot-pool's resting state for unoccupied slots."""
        return tuple(dec.initial_carry(batch)
                     for dec in self.decoder_rnns)

    def decode_params(self) -> Params:
        return self.get_variables()["params"]

    # --------------------------------------------------------------- infer
    def infer(self, enc_ids: np.ndarray, start_sign: int,
              max_seq_len: int = 30, stop_sign: Optional[int] = None,
              early_exit: bool = True, return_steps: bool = False):
        """Greedy decode as ONE jitted device program.

        With a ``stop_sign`` the decode runs as a ``lax.while_loop``
        that exits the moment EVERY sequence has emitted the stop
        token — a batch that finishes at step 5 pays 5 iterations, not
        ``max_seq_len`` — and the masking (everything after the first
        stop token reads ``stop_sign``) happens in the device program.
        The output is bit-identical to the historical
        scan-then-host-mask path (``early_exit=False`` keeps that
        exact whole-sequence scan, which is also the honest "naive"
        baseline the serving bench compares against).
        ``return_steps=True`` additionally returns how many decode
        iterations actually executed."""
        params = self.get_variables()["params"]
        enc_ids = jnp.asarray(enc_ids, jnp.int32)

        def decode_scan(params, enc_ids):
            carries = self.prefill(params, enc_ids)
            batch = enc_ids.shape[0]
            tok0 = jnp.full((batch,), start_sign, jnp.int32)

            def step(carry_state, _):
                tok, carries = carry_state
                nxt, new_carries = self.decode_step(params, tok,
                                                    carries)
                return (nxt, new_carries), nxt

            _, toks = jax.lax.scan(step, (tok0, carries), None,
                                   length=max_seq_len)
            return jnp.swapaxes(toks, 0, 1)

        def decode_early_exit(params, enc_ids):
            carries = self.prefill(params, enc_ids)
            batch = enc_ids.shape[0]
            tok0 = jnp.full((batch,), start_sign, jnp.int32)
            # rows never written (the loop exited first) already hold
            # the masked value, exactly like the host-side mask did
            out0 = jnp.full((batch, max_seq_len), stop_sign, jnp.int32)
            stopped0 = jnp.zeros((batch,), bool)

            def cond(state):
                i, _tok, _carries, _out, stopped = state
                return (i < max_seq_len) & ~jnp.all(stopped)

            def body(state):
                i, tok, carries, out, stopped = state
                nxt, new_carries = self.decode_step(params, tok,
                                                    carries)
                # a stopped lane keeps reading stop_sign; live lanes
                # record the raw argmax (which may BE the stop token —
                # included, like the cumsum mask included it)
                emit = jnp.where(stopped, stop_sign, nxt)
                out = out.at[:, i].set(emit)
                # the raw token feeds back even on stopped lanes, so
                # executed iterations match the scan path bit-for-bit
                return (i + 1, nxt, new_carries, out,
                        stopped | (emit == stop_sign))

            i, _tok, _carries, out, _stopped = jax.lax.while_loop(
                cond, body, (jnp.int32(0), tok0, carries, out0,
                             stopped0))
            return out, i

        from analytics_zoo_tpu.compile import engine_jit
        if stop_sign is not None and early_exit:
            out, steps = engine_jit(
                decode_early_exit,
                key_hint="seq2seq_decode_early_exit")(params, enc_ids)
            out, steps = np.asarray(out), int(steps)
        else:
            out = np.asarray(engine_jit(
                decode_scan, key_hint="seq2seq_decode")(params,
                                                        enc_ids))
            steps = max_seq_len
            if stop_sign is not None:
                # mask everything after the first stop token
                stopped = np.cumsum(out == stop_sign, axis=1) > 0
                out = np.where(stopped, stop_sign, out)
        return (out, steps) if return_steps else out
