"""Text classifier (ref:
zoo/models/textclassification/TextClassifier.scala:34-192): embedding →
encoder (CNN / LSTM / GRU) → dense head."""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution1D, Dense, Dropout, Embedding, Flatten,
    GlobalMaxPooling1D, GRU, LSTM, WordEmbedding,
)


class TextClassifier(ZooModel):
    """encoder: "cnn" | "lstm" | "gru" (TextClassifier.scala encoder
    arg); with optional pretrained glove embeddings."""

    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 max_words_num: int = 5000,
                 embedding_matrix: Optional[np.ndarray] = None):
        self.class_num = int(class_num)
        self.token_length = int(token_length)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder.lower()
        self.encoder_output_dim = int(encoder_output_dim)
        self.max_words_num = int(max_words_num)
        self.embedding_matrix = embedding_matrix
        super().__init__()

    def build_model(self):
        inp = Input(shape=(self.sequence_length,))
        if self.embedding_matrix is not None:
            x = WordEmbedding(self.embedding_matrix, trainable=False)(inp)
        else:
            x = Embedding(self.max_words_num + 1, self.token_length,
                          init="uniform")(inp)
        if self.encoder == "cnn":
            x = Convolution1D(self.encoder_output_dim, 5,
                              activation="relu")(x)
            x = GlobalMaxPooling1D()(x)
        elif self.encoder == "lstm":
            x = LSTM(self.encoder_output_dim)(x)
        elif self.encoder == "gru":
            x = GRU(self.encoder_output_dim)(x)
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}; "
                             "use cnn|lstm|gru")
        x = Dropout(0.2)(x)
        x = Dense(128, activation="relu")(x)
        out = Dense(self.class_num)(x)
        return Model(inp, out)
