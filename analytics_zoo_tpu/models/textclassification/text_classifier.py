"""Text classifier (ref:
zoo/models/textclassification/TextClassifier.scala:34-192): embedding →
encoder (CNN / LSTM / GRU / transformer) → dense head.

The ``transformer`` encoder is the long-context opt-in: its
self-attention routes through ``parallel/ring_attention.py`` whenever
the mesh's ``seq`` axis is populated (MultiHeadSelfAttention's "auto"
sequence parallelism), so sequence length scales across the ICI ring
instead of capping at one chip's HBM — the capability the reference's
single-node encoders lack."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution1D, Dense, Dropout, Embedding, Flatten,
    GlobalMaxPooling1D, GRU, LSTM, WordEmbedding,
)


class TextClassifier(ZooModel):
    """encoder: "cnn" | "lstm" | "gru" (TextClassifier.scala encoder
    arg) | "transformer" (long-context self-attention; ring-parallel
    over a populated ``seq`` mesh axis); with optional pretrained
    glove embeddings.  ``n_head``/``n_block`` apply to the transformer
    encoder only; its width is ``token_length`` (residual stream), the
    head keeps ``encoder_output_dim``."""

    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 max_words_num: int = 5000,
                 embedding_matrix: Optional[np.ndarray] = None,
                 n_head: int = 4, n_block: int = 1):
        self.class_num = int(class_num)
        self.token_length = int(token_length)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder.lower()
        self.encoder_output_dim = int(encoder_output_dim)
        self.max_words_num = int(max_words_num)
        self.embedding_matrix = embedding_matrix
        self.n_head = int(n_head)
        self.n_block = int(n_block)
        if self.encoder == "transformer" and \
                self.token_length % self.n_head:
            raise ValueError(
                f"token_length {self.token_length} must divide into "
                f"n_head {self.n_head} heads")
        super().__init__()

    def build_model(self):
        inp = Input(shape=(self.sequence_length,))
        if self.embedding_matrix is not None:
            x = WordEmbedding(self.embedding_matrix, trainable=False)(inp)
        else:
            x = Embedding(self.max_words_num + 1, self.token_length,
                          init="uniform")(inp)
        if self.encoder == "cnn":
            x = Convolution1D(self.encoder_output_dim, 5,
                              activation="relu")(x)
            x = GlobalMaxPooling1D()(x)
        elif self.encoder == "lstm":
            x = LSTM(self.encoder_output_dim)(x)
        elif self.encoder == "gru":
            x = GRU(self.encoder_output_dim)(x)
        elif self.encoder == "transformer":
            x = self._transformer_encoder(inp, x)
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}; "
                             "use cnn|lstm|gru|transformer")
        x = Dropout(0.2)(x)
        x = Dense(128, activation="relu")(x)
        out = Dense(self.class_num)(x)
        return Model(inp, out)

    def _transformer_encoder(self, inp, x):
        """Learned positions + ``n_block`` encoder blocks + max-pool +
        a fused LayerNorm→GeLU projection head.  Attention is
        MultiHeadSelfAttention with "auto" parallelism: on a mesh with
        ``seq`` > 1 it computes via the ppermute ring
        (parallel/ring_attention.py) — sequence sharded over ICI —
        and single-device it takes the flash/dense kernel."""
        from analytics_zoo_tpu.pipeline.api.keras.layers.attention import (
            transformer_block)
        from analytics_zoo_tpu.pipeline.api.keras.layers.core import (
            Lambda)
        from analytics_zoo_tpu.pipeline.api.keras.layers.normalization \
            import LayerNorm
        d = self.token_length
        # position ids derived in-graph from the token input (no extra
        # model input): iota over the sequence axis
        pos_ids = Lambda(
            lambda t: jnp.broadcast_to(
                jnp.arange(t.shape[1], dtype=jnp.int32)[None, :],
                t.shape),
            output_shape=(self.sequence_length,))(inp)
        pos_e = Embedding(self.sequence_length, d,
                          init="normal")(pos_ids)
        from analytics_zoo_tpu.pipeline.api.keras.layers.merge import (
            Merge)
        x = Merge(mode="sum")([x, pos_e])
        for _ in range(self.n_block):
            x = transformer_block(x, None, d, self.n_head, 4 * d,
                                  dropout=0.1, causal=False)
        x = GlobalMaxPooling1D()(x)
        # fused LayerNorm→GeLU epilogue (ops/fused.py layernorm_act)
        x = LayerNorm(activation="gelu")(x)
        return Dense(self.encoder_output_dim, activation="relu")(x)
