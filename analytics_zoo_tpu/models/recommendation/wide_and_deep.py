"""Wide & Deep recommender.

Reference: zoo/models/recommendation/WideAndDeep.scala:101 and the
feature engineering in Utils.scala:325 — a "wide" linear part over
sparse crossed/base features and a "deep" part over category embeddings
+ continuous columns, joined into class logits.  ``ColumnFeatureInfo``
mirrors the reference's column-spec object.

TPU redesign of the wide part: instead of a SparseDense over a huge
one-hot vector (CPU-sparse trick), the wide weights are an embedding
table gathered by active-feature indices and summed — identical math,
MXU/HBM friendly, and the gradient is naturally sparse.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

from analytics_zoo_tpu.models.recommendation.recommender import Recommender
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, Embedding, Flatten, Lambda, Merge,
)


@dataclasses.dataclass
class ColumnFeatureInfo:
    """Column spec (ref WideAndDeep ColumnFeatureInfo, Utils.scala)."""
    wide_base_cols: Sequence[str] = ()
    wide_base_dims: Sequence[int] = ()
    wide_cross_cols: Sequence[str] = ()
    wide_cross_dims: Sequence[int] = ()
    indicator_cols: Sequence[str] = ()
    indicator_dims: Sequence[int] = ()
    embed_cols: Sequence[str] = ()
    embed_in_dims: Sequence[int] = ()
    embed_out_dims: Sequence[int] = ()
    continuous_cols: Sequence[str] = ()

    @property
    def wide_dims(self) -> List[int]:
        return list(self.wide_base_dims) + list(self.wide_cross_dims)


class WideAndDeep(Recommender):
    """model_type: "wide_n_deep" | "wide" | "deep"."""

    def __init__(self, class_num: int, column_info: ColumnFeatureInfo,
                 model_type: str = "wide_n_deep",
                 hidden_layers: Sequence[int] = (40, 20, 10)):
        self.class_num = int(class_num)
        self.column_info = column_info
        self.model_type = model_type
        self.hidden_layers = list(hidden_layers)
        super().__init__()

    # ------------------------------------------------------------ building
    def build_model(self):
        info = self.column_info
        inputs = []
        parts = []

        if self.model_type in ("wide", "wide_n_deep"):
            n_wide = len(info.wide_dims)
            assert n_wide > 0, "wide model needs wide_base/cross cols"
            # one index per wide column, pre-offset into a shared table
            wide_in = Input(shape=(n_wide,))
            inputs.append(wide_in)
            total = int(sum(info.wide_dims)) + 1
            wide_emb = Embedding(total, self.class_num, init="zero")(wide_in)
            wide_out = Lambda(lambda t: t.sum(axis=1),
                              output_shape=(self.class_num,))(wide_emb)
            parts.append(wide_out)

        if self.model_type in ("deep", "wide_n_deep"):
            deep_parts = []
            n_ind = len(info.indicator_cols)
            n_emb = len(info.embed_cols)
            n_cont = len(info.continuous_cols)
            if n_ind:
                ind_in = Input(shape=(int(sum(info.indicator_dims)),))
                inputs.append(ind_in)
                deep_parts.append(ind_in)
            if n_emb:
                emb_in = Input(shape=(n_emb,))
                inputs.append(emb_in)
                for j in range(n_emb):
                    col = Lambda(lambda t, j=j: t[:, j:j + 1],
                                 output_shape=(1,))(emb_in)
                    e = Embedding(int(info.embed_in_dims[j]) + 1,
                                  int(info.embed_out_dims[j]),
                                  init="normal")(col)
                    deep_parts.append(Flatten()(e))
            if n_cont:
                cont_in = Input(shape=(n_cont,))
                inputs.append(cont_in)
                deep_parts.append(cont_in)
            deep = deep_parts[0] if len(deep_parts) == 1 else \
                Merge(mode="concat")(deep_parts)
            for k, units in enumerate(self.hidden_layers):
                deep = Dense(units, activation="relu")(deep)
            deep_out = Dense(self.class_num)(deep)
            parts.append(deep_out)

        out = parts[0] if len(parts) == 1 else \
            Merge(mode="sum")(parts)
        return Model(inputs, out)

    # -------------------------------------------------------------- features
    def wide_indices(self, columns: dict) -> np.ndarray:
        """Map raw per-column category ids to offsets into the shared
        wide table (+1 reserves 0 as padding)."""
        info = self.column_info
        cols = list(info.wide_base_cols) + list(info.wide_cross_cols)
        dims = info.wide_dims
        out = []
        offset = 1
        for name, dim in zip(cols, dims):
            v = np.asarray(columns[name]).astype(np.int64) % dim
            out.append(v + offset)
            offset += dim
        return np.stack(out, axis=1).astype(np.int32)

    def features_from_columns(self, columns: dict) -> List[np.ndarray]:
        """Assemble model inputs from a dict of named columns (the
        DataFrame-row → feature path of Utils.scala:325)."""
        info = self.column_info
        feats = []
        if self.model_type in ("wide", "wide_n_deep"):
            feats.append(self.wide_indices(columns))
        if self.model_type in ("deep", "wide_n_deep"):
            if info.indicator_cols:
                blocks = []
                for name, dim in zip(info.indicator_cols,
                                     info.indicator_dims):
                    v = np.asarray(columns[name]).astype(np.int64) % dim
                    oh = np.zeros((len(v), dim), np.float32)
                    oh[np.arange(len(v)), v] = 1.0
                    blocks.append(oh)
                feats.append(np.concatenate(blocks, axis=1))
            if info.embed_cols:
                feats.append(np.stack(
                    [np.asarray(columns[c]).astype(np.int32)
                     for c in info.embed_cols], axis=1))
            if info.continuous_cols:
                feats.append(np.stack(
                    [np.asarray(columns[c]).astype(np.float32)
                     for c in info.continuous_cols], axis=1))
        return feats

    def pair_features(self, user_ids, item_ids):
        raise NotImplementedError(
            "WideAndDeep consumes arbitrary feature columns; use "
            "features_from_columns")
