"""Recommender base API.

Reference: zoo/models/recommendation/Recommender.scala:46-105 —
``predictUserItemPair``, ``recommendForUser``, ``recommendForItem`` over
RDD[UserItemFeature].  TPU-natively these are batched predict calls over
columnar arrays; ranking is a device-side top-k.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel


@dataclasses.dataclass
class UserItemFeature:
    user_id: int
    item_id: int
    features: dict          # model-ready input columns


@dataclasses.dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


class Recommender(ZooModel):
    """Subclasses must map (user_ids, item_ids) -> model inputs via
    ``pair_features``."""

    def pair_features(self, user_ids: np.ndarray, item_ids: np.ndarray):
        raise NotImplementedError

    def predict_user_item_pair(
            self, feature_pairs: Sequence[UserItemFeature],
            batch_size: int = 2048) -> List[UserItemPrediction]:
        users = np.array([p.user_id for p in feature_pairs])
        items = np.array([p.item_id for p in feature_pairs])
        probs = self._pair_scores(users, items, batch_size)
        preds = np.argmax(probs, axis=-1)
        return [UserItemPrediction(int(u), int(i), int(c) + 1,
                                   float(p[c]))
                for u, i, c, p in zip(users, items, preds, probs)]

    def _pair_scores(self, users, items, batch_size):
        x = self.pair_features(users, items)
        out = self.predict(x, batch_size=batch_size)
        return np.asarray(out)

    def recommend_for_user(self, user_ids: Sequence[int],
                           candidate_items: Sequence[int], max_items: int,
                           batch_size: int = 4096):
        """Top ``max_items`` items per user by positive-class score."""
        items = np.asarray(candidate_items)
        result = {}
        for u in user_ids:
            users = np.full(len(items), u)
            probs = self._pair_scores(users, items, batch_size)
            score = probs[:, -1] if probs.ndim > 1 else probs.ravel()
            top = np.argsort(-score)[:max_items]
            result[u] = [UserItemPrediction(int(u), int(items[j]),
                                            int(np.argmax(probs[j])) + 1
                                            if probs.ndim > 1 else 1,
                                            float(score[j]))
                         for j in top]
        return result

    def recommend_for_item(self, item_ids: Sequence[int],
                           candidate_users: Sequence[int], max_users: int,
                           batch_size: int = 4096):
        users = np.asarray(candidate_users)
        result = {}
        for it in item_ids:
            items = np.full(len(users), it)
            probs = self._pair_scores(users, items, batch_size)
            score = probs[:, -1] if probs.ndim > 1 else probs.ravel()
            top = np.argsort(-score)[:max_users]
            result[it] = [UserItemPrediction(int(users[j]), int(it),
                                             int(np.argmax(probs[j])) + 1
                                             if probs.ndim > 1 else 1,
                                             float(score[j]))
                          for j in top]
        return result
