"""Neural Collaborative Filtering (NCF).

Reference: zoo/models/recommendation/NeuralCF.scala:45-138 — GMF branch
(elementwise product of user/item embeddings) + MLP branch (concat
embeddings through hidden layers), merged into a softmax over
``numClasses``.  The MLPerf-cited NCF workload (BASELINE.md config 1)
uses the binary implicit-feedback variant.

TPU notes: the whole model is embedding gathers + small matmuls — one
fused XLA program; batches in the tens of thousands keep the MXU busy.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from analytics_zoo_tpu.models.recommendation.recommender import Recommender
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, Embedding, Flatten, Merge,
)


class NeuralCF(Recommender):
    def __init__(self, user_count: int, item_count: int, class_num: int = 2,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        self.user_count = int(user_count)
        self.item_count = int(item_count)
        self.class_num = int(class_num)
        self.user_embed = int(user_embed)
        self.item_embed = int(item_embed)
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = int(mf_embed)
        super().__init__()

    def build_model(self):
        # ids arrive 1-based as in the reference; tables sized +1
        user_in = Input(shape=(1,))
        item_in = Input(shape=(1,))

        mlp_user = Flatten()(Embedding(
            self.user_count + 1, self.user_embed, init="normal")(user_in))
        mlp_item = Flatten()(Embedding(
            self.item_count + 1, self.item_embed, init="normal")(item_in))
        mlp = Merge(mode="concat")([mlp_user, mlp_item])
        for units in self.hidden_layers:
            mlp = Dense(units, activation="relu")(mlp)

        if self.include_mf:
            mf_user = Flatten()(Embedding(
                self.user_count + 1, self.mf_embed, init="normal")(user_in))
            mf_item = Flatten()(Embedding(
                self.item_count + 1, self.mf_embed, init="normal")(item_in))
            mf = Merge(mode="mul")([mf_user, mf_item])
            joined = Merge(mode="concat")([mf, mlp])
        else:
            joined = mlp
        out = Dense(self.class_num)(joined)   # logits; pair with *_with_logits
        return Model([user_in, item_in], out)

    def pair_features(self, user_ids: np.ndarray, item_ids: np.ndarray):
        return [user_ids.reshape(-1, 1).astype(np.int32),
                item_ids.reshape(-1, 1).astype(np.int32)]
