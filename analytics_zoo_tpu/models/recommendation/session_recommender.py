"""Session-based RNN recommender.

Reference: zoo/models/recommendation/SessionRecommender.scala:45-209 —
GRU over the item-click session (optionally + a second GRU over user
purchase history), softmax over the item vocabulary;
``recommend_for_session`` returns top-k next items.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, Embedding, Merge,
)
from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import GRU


class SessionRecommender(ZooModel):
    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 5, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 10):
        self.item_count = int(item_count)
        self.item_embed = int(item_embed)
        self.rnn_hidden_layers = list(rnn_hidden_layers)
        self.session_length = int(session_length)
        self.include_history = include_history
        self.mlp_hidden_layers = list(mlp_hidden_layers)
        self.history_length = int(history_length)
        super().__init__()

    def build_model(self):
        session_in = Input(shape=(self.session_length,))
        x = Embedding(self.item_count + 1, self.item_embed,
                      init="uniform")(session_in)
        for h in self.rnn_hidden_layers[:-1]:
            x = GRU(h, return_sequences=True)(x)
        rnn_out = GRU(self.rnn_hidden_layers[-1])(x)
        inputs = [session_in]
        if self.include_history:
            his_in = Input(shape=(self.history_length,))
            inputs.append(his_in)
            h = Embedding(self.item_count + 1, self.item_embed,
                          init="uniform")(his_in)
            # mean-pool purchase history then MLP
            from analytics_zoo_tpu.pipeline.api.keras.layers import Lambda
            h = Lambda(lambda t: t.mean(axis=1),
                       output_shape=(self.item_embed,))(h)
            for units in self.mlp_hidden_layers:
                h = Dense(units, activation="relu")(h)
            rnn_out = Merge(mode="concat")([rnn_out, h])
        out = Dense(self.item_count + 1)(rnn_out)   # logits over items
        return Model(inputs, out)

    # ------------------------------------------------------------ inference
    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 5,
                              zero_based_label: bool = False,
                              history: Optional[np.ndarray] = None,
                              batch_size: int = 1024
                              ) -> List[List[Tuple[int, float]]]:
        x = [sessions.astype(np.int32)]
        if self.include_history:
            assert history is not None, "model was built with history input"
            x.append(history.astype(np.int32))
        logits = np.asarray(self.predict(x, batch_size=batch_size))
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = e / e.sum(axis=-1, keepdims=True)
        top = np.argsort(-probs, axis=-1)[:, :max_items]
        off = 0 if zero_based_label else 0  # item ids are already 1-based
        return [[(int(i) + off, float(p[i])) for i in row]
                for row, p in zip(top, probs)]
