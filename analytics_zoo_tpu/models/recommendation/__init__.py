from analytics_zoo_tpu.models.recommendation.recommender import (
    Recommender, UserItemFeature, UserItemPrediction,
)
from analytics_zoo_tpu.models.recommendation.neuralcf import NeuralCF
from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
    ColumnFeatureInfo, WideAndDeep,
)
from analytics_zoo_tpu.models.recommendation.session_recommender import (
    SessionRecommender,
)

__all__ = [
    "Recommender", "UserItemFeature", "UserItemPrediction", "NeuralCF",
    "ColumnFeatureInfo", "WideAndDeep", "SessionRecommender",
]
