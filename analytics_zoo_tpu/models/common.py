"""Model-zoo base classes.

Reference: ``ZooModel`` (zoo/models/common/ZooModel.scala:37-154) —
build/saveModel/loadModel/predictClasses — and ``KerasZooModel``
(common/KerasZooModel.scala:183) adding the KerasNet training surface.

Here a ZooModel *is* a thin facade over an inner KerasNet graph built by
``build_model``; compile/fit/evaluate/predict/save delegate to it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ZooModel:
    """Base: subclasses implement ``build_model() -> KerasNet``."""

    def __init__(self, **kwargs):
        self.model = self.build_model()

    def build_model(self):
        raise NotImplementedError

    # ------------------------------------------------------------ delegate
    def compile(self, *args, **kwargs):
        self.model.compile(*args, **kwargs)
        return self

    def fit(self, *args, **kwargs):
        return self.model.fit(*args, **kwargs)

    def evaluate(self, *args, **kwargs):
        return self.model.evaluate(*args, **kwargs)

    def predict(self, *args, **kwargs):
        return self.model.predict(*args, **kwargs)

    def predict_classes(self, *args, **kwargs):
        return self.model.predict_classes(*args, **kwargs)

    def summary(self):
        return self.model.summary()

    def quantize(self, calib_data, **kwargs):
        """Calibrated int8 conversion (KerasNet.quantize): after this,
        predict/recommend/serving run the int8 MXU path end-to-end."""
        self.model.quantize(calib_data, **kwargs)
        return self

    @property
    def is_quantized(self) -> bool:
        return self.model.is_quantized

    def get_variables(self):
        return self.model.get_variables()

    def set_variables(self, variables):
        self.model.set_variables(variables)

    def save_model(self, path: str, over_write: bool = True):
        self.model.save_model(path, over_write=over_write)

    def load_weights(self, path: str):
        self.model.load_weights(path)
        return self
