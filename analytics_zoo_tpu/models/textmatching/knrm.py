"""KNRM — kernel-pooling neural ranking model.

Reference: zoo/models/textmatching/KNRM.scala:60-192: shared word
embedding for query and doc, cosine translation matrix, RBF kernel
pooling (mu from 0.9 to -0.9 plus exact-match kernel), log-kernel sum
over the query axis, linear score head.

TPU note: the translation matrix is one batched matmul (B, Q, D_doc)
and every kernel is an elementwise exp — the whole model fuses into a
couple of XLA kernels.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.engine import Layer, Params
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, Embedding, WordEmbedding,
)


class KernelPooling(Layer):
    """Cosine translation + RBF kernel pooling."""

    def __init__(self, text1_length: int, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001, **kwargs):
        super().__init__(**kwargs)
        self.text1_length = text1_length
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)

    def call(self, params, inputs, training=False, rng=None):
        q, d = inputs                       # (B, Q, E), (B, D, E)
        qn = q / jnp.maximum(
            jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-8)
        dn = d / jnp.maximum(
            jnp.linalg.norm(d, axis=-1, keepdims=True), 1e-8)
        trans = jnp.einsum("bqe,bde->bqd", qn, dn)   # cosine matrix
        feats = []
        for i in range(self.kernel_num):
            mu = 1.0 / (self.kernel_num - 1) + (2.0 * i) / (
                self.kernel_num - 1) - 1.0
            sigma = self.sigma
            if mu > 1.0 - 1e-6:
                sigma = self.exact_sigma
                mu = 1.0
            k = jnp.exp(-jnp.square(trans - mu) / (2 * sigma * sigma))
            # sum over doc axis, log, sum over query axis
            kq = jnp.sum(k, axis=2)
            feats.append(jnp.sum(jnp.log1p(kq), axis=1))
        return jnp.stack(feats, axis=1)     # (B, kernel_num)

    def compute_output_shape(self, input_shape):
        return (input_shape[0][0], self.kernel_num)


class KNRM(ZooModel):
    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: int = 10000, embed_size: int = 50,
                 embedding_matrix: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        self.text1_length = int(text1_length)
        self.text2_length = int(text2_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embedding_matrix = embedding_matrix
        self.train_embed = train_embed
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)
        assert target_mode in ("ranking", "classification")
        self.target_mode = target_mode
        super().__init__()

    def build_model(self):
        q_in = Input(shape=(self.text1_length,))
        d_in = Input(shape=(self.text2_length,))
        if self.embedding_matrix is not None:
            embed = WordEmbedding(self.embedding_matrix,
                                  trainable=self.train_embed)
        else:
            embed = Embedding(self.vocab_size + 1, self.embed_size,
                              init="uniform")
        q = embed(q_in)
        d = embed(d_in)
        pooled = KernelPooling(self.text1_length, self.kernel_num,
                               self.sigma, self.exact_sigma)([q, d])
        out = Dense(1, activation=(
            "sigmoid" if self.target_mode == "classification" else None))(
            pooled)
        return Model([q_in, d_in], out)

    def score_pairs(self, query_ids: np.ndarray, doc_ids: np.ndarray,
                    batch_size: int = 1024) -> np.ndarray:
        return np.asarray(self.predict(
            [query_ids.astype(np.int32), doc_ids.astype(np.int32)],
            batch_size=batch_size)).ravel()
