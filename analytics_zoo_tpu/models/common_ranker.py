"""Ranking evaluation — mean average precision and NDCG@k over grouped
query/candidate relations (ref: zoo/models/common/Ranker.scala:175,
``evaluateMAP`` / ``evaluateNDCG``)."""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np


def _grouped(relations: Sequence[Tuple], scores: np.ndarray):
    groups: Dict = {}
    for (id1, _id2, label), s in zip(relations, scores):
        groups.setdefault(id1, []).append((float(s), int(label)))
    return groups


def evaluate_map(relations: Sequence[Tuple], scores: np.ndarray) -> float:
    """relations: (query_id, doc_id, label); scores aligned."""
    groups = _grouped(relations, scores)
    aps = []
    for items in groups.values():
        ranked = sorted(items, key=lambda t: -t[0])
        hits, precisions = 0, []
        for rank, (_, label) in enumerate(ranked, start=1):
            if label > 0:
                hits += 1
                precisions.append(hits / rank)
        if precisions:
            aps.append(float(np.mean(precisions)))
    return float(np.mean(aps)) if aps else 0.0


def evaluate_ndcg(relations: Sequence[Tuple], scores: np.ndarray,
                  k: int = 3) -> float:
    groups = _grouped(relations, scores)
    vals = []
    for items in groups.values():
        ranked = sorted(items, key=lambda t: -t[0])[:k]
        dcg = sum((2 ** label - 1) / np.log2(rank + 1)
                  for rank, (_, label) in enumerate(ranked, start=1))
        ideal = sorted((l for _, l in items), reverse=True)[:k]
        idcg = sum((2 ** l - 1) / np.log2(r + 1)
                   for r, l in enumerate(ideal, start=1))
        if idcg > 0:
            vals.append(dcg / idcg)
    return float(np.mean(vals)) if vals else 0.0
