"""SSD detection graphs + detector wrapper.

Reference: objectdetection/ssd/SSDGraph.scala:220 + SSD.scala:214 — VGG
backbone with extra feature layers and per-scale loc/conf heads; SSDVGG
300 config.  Heads emit (B, P, 4) locations and (B, P, C) class scores
over the stacked prior set; decode + NMS produce final detections.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.image.objectdetection.bbox import decode_boxes
from analytics_zoo_tpu.models.image.objectdetection.nms import (
    multiclass_nms, nms,
)
from analytics_zoo_tpu.models.image.objectdetection.prior_box import (
    num_priors_per_cell, ssd_priors,
)
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, BatchNormalization, Convolution2D, Lambda, MaxPooling2D,
    Merge,
)


def _conv_bn(x, f, k, stride=1, border="same"):
    x = Convolution2D(f, k, k, subsample=(stride, stride),
                      border_mode=border, bias=False)(x)
    x = BatchNormalization()(x)
    return Activation("relu")(x)


def _head(feats, n_priors_cell: Sequence[int], num_classes: int):
    """Per-scale loc/conf conv heads, flattened and concatenated."""
    locs, confs = [], []
    for x, k in zip(feats, n_priors_cell):
        loc = Convolution2D(k * 4, 3, 3, border_mode="same")(x)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same")(x)
        locs.append(Lambda(
            lambda t: t.reshape(t.shape[0], -1, 4))(loc))
        confs.append(Lambda(
            lambda t, c=num_classes: t.reshape(t.shape[0], -1, c))(conf))
    loc = locs[0] if len(locs) == 1 else Merge(mode="concat",
                                              concat_axis=1)(locs)
    conf = confs[0] if len(confs) == 1 else Merge(mode="concat",
                                                  concat_axis=1)(confs)
    return loc, conf


_SSD300_SPECS = dict(
    fmap_sizes=(38, 19, 10, 5, 3, 1),
    min_sizes=(30, 60, 111, 162, 213, 264),
    max_sizes=(60, 111, 162, 213, 264, 315),
    aspect_ratios=((2.0,), (2.0, 3.0), (2.0, 3.0), (2.0, 3.0),
                   (2.0,), (2.0,)),
)


def ssd_vgg300(num_classes: int = 21) -> Tuple[Model, np.ndarray]:
    """SSD300 with a VGG16-style backbone (SSDVGG default config)."""
    inp = Input(shape=(300, 300, 3))
    x = _conv_bn(inp, 64, 3)
    x = _conv_bn(x, 64, 3)
    x = MaxPooling2D(border_mode="same")(x)          # 150
    x = _conv_bn(x, 128, 3)
    x = _conv_bn(x, 128, 3)
    x = MaxPooling2D(border_mode="same")(x)          # 75
    x = _conv_bn(x, 256, 3)
    x = _conv_bn(x, 256, 3)
    x = _conv_bn(x, 256, 3)
    x = MaxPooling2D(pool_size=(2, 2), strides=(2, 2),
                     border_mode="same")(x)          # 38
    x = _conv_bn(x, 512, 3)
    x = _conv_bn(x, 512, 3)
    f38 = _conv_bn(x, 512, 3)                        # conv4_3: 38x38
    x = MaxPooling2D(border_mode="same")(f38)        # 19
    x = _conv_bn(x, 512, 3)
    x = _conv_bn(x, 512, 3)
    x = _conv_bn(x, 512, 3)
    x = _conv_bn(x, 1024, 3)
    f19 = _conv_bn(x, 1024, 1)                       # fc7: 19x19
    x = _conv_bn(f19, 256, 1)
    f10 = _conv_bn(x, 512, 3, stride=2)              # 10x10
    x = _conv_bn(f10, 128, 1)
    f5 = _conv_bn(x, 256, 3, stride=2)               # 5x5
    x = _conv_bn(f5, 128, 1)
    f3 = _conv_bn(x, 256, 3, stride=2)               # 3x3
    x = _conv_bn(f3, 128, 1)
    f1 = _conv_bn(x, 256, 3, stride=2, border="same")  # 2x2 -> crop
    from analytics_zoo_tpu.pipeline.api.keras.layers import Cropping2D
    f1 = Cropping2D(((0, 1), (0, 1)))(f1)            # 1x1

    s = _SSD300_SPECS
    k_cells = [num_priors_per_cell(mx, ars)
               for mx, ars in zip(s["max_sizes"], s["aspect_ratios"])]
    loc, conf = _head([f38, f19, f10, f5, f3, f1], k_cells, num_classes)
    priors = ssd_priors(300, s["fmap_sizes"], s["min_sizes"],
                        s["max_sizes"], s["aspect_ratios"])
    return Model(inp, [loc, conf]), priors


def ssd_lite(num_classes: int = 4, image_size: int = 64
             ) -> Tuple[Model, np.ndarray]:
    """Small SSD for tests / tiny datasets: 3 scales."""
    inp = Input(shape=(image_size, image_size, 3))
    x = _conv_bn(inp, 16, 3, stride=2)     # 32
    x = _conv_bn(x, 32, 3)
    f1 = _conv_bn(x, 32, 3, stride=2)      # 16
    f2 = _conv_bn(f1, 64, 3, stride=2)     # 8
    f3 = _conv_bn(f2, 64, 3, stride=2)     # 4
    fmaps = (image_size // 4, image_size // 8, image_size // 16)
    min_sizes = (image_size * 0.15, image_size * 0.35, image_size * 0.6)
    max_sizes = (image_size * 0.35, image_size * 0.6, image_size * 0.9)
    ars = ((2.0,), (2.0,), (2.0,))
    k_cells = [num_priors_per_cell(mx, a)
               for mx, a in zip(max_sizes, ars)]
    loc, conf = _head([f1, f2, f3], k_cells, num_classes)
    priors = ssd_priors(image_size, fmaps, min_sizes, max_sizes, ars)
    return Model(inp, [loc, conf]), priors


class SSDDetector:
    """Detection wrapper: forward → decode → NMS (the predictImageSet
    + postprocess role of ImageModel/SSD).

    ``per_class_nms=False`` (default): best-non-background-class NMS —
    cheap, one NMS pass per image.  ``per_class_nms=True``: the
    torchvision/COCO postprocess — NMS per class with cross-class
    results (overlapping objects of DIFFERENT classes both survive),
    bounded by ``topk_per_class`` candidates per class."""

    def __init__(self, model: Model, priors: np.ndarray,
                 num_classes: int, score_threshold: float = 0.3,
                 iou_threshold: float = 0.45, max_detections: int = 100,
                 per_class_nms: bool = False, topk_per_class: int = 400):
        self.model = model
        self.priors = jnp.asarray(priors)
        self.num_classes = num_classes
        self.score_threshold = score_threshold
        self.iou_threshold = iou_threshold
        self.max_detections = max_detections
        self.per_class_nms = per_class_nms
        self.topk_per_class = topk_per_class
        self._fn = None

    def _build(self):
        model, priors = self.model, self.priors
        k_iou, k_max, k_score = (self.iou_threshold, self.max_detections,
                                 self.score_threshold)
        per_class, k_topk = self.per_class_nms, self.topk_per_class

        def detect(params, state, x):
            (loc, conf), _ = model.apply(params, x, state=state,
                                         training=False)
            boxes = decode_boxes(loc, priors)          # (B,P,4)
            probs = jax.nn.softmax(conf, axis=-1)      # (B,P,C)

            def per_image(b, p):
                if per_class:
                    return multiclass_nms(b, p, k_iou, k_score,
                                          k_topk, k_max)
                score = jnp.max(p[:, 1:], axis=-1)     # best non-bg
                label = jnp.argmax(p[:, 1:], axis=-1) + 1
                idx, valid = nms(b, score, k_iou, k_max, k_score)
                safe = jnp.maximum(idx, 0)
                return (b[safe], score[safe],
                        label[safe].astype(jnp.int32), valid)

            return jax.vmap(per_image)(boxes, probs)

        from analytics_zoo_tpu.compile import engine_jit
        self._fn = engine_jit(detect, key_hint="ssd_detect")

    def detect(self, images: np.ndarray):
        """-> list per image of (boxes (k,4), scores (k,), labels (k,))."""
        if self._fn is None:
            self._build()
        v = self.model.get_variables()
        boxes, scores, labels, valid = self._fn(
            v["params"], v["state"], jnp.asarray(images))
        out = []
        for b, s, l, m in zip(np.asarray(boxes), np.asarray(scores),
                              np.asarray(labels), np.asarray(valid)):
            out.append((b[m], s[m], l[m]))
        return out
