"""Detection evaluation: PascalVOC-style mean average precision
(ref: objectdetection/evaluation/ PascalVocEvaluator /
MeanAveragePrecision)."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def _voc_ap(recall, precision, use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(precision[recall >= t]) if np.any(recall >= t) \
                else 0.0
            ap += p / 11.0
        return float(ap)
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


class MeanAveragePrecision:
    """Accumulate per-image detections + ground truths, compute mAP."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.use_07 = use_07_metric
        self._dets: List[Tuple] = []    # (img, box, score, label)
        self._gts: List[Tuple] = []     # (img, box, label, difficult)
        self._img = 0

    def add(self, det_boxes, det_scores, det_labels,
            gt_boxes, gt_labels, gt_difficult=None) -> None:
        """``gt_difficult`` follows PascalVOC semantics: difficult
        ground truths are excluded from the positive count and a
        detection matching one is IGNORED (neither TP nor FP) —
        ref PascalVocEvaluator.scala's difficult handling."""
        i = self._img
        self._img += 1
        if gt_difficult is None:
            gt_difficult = [False] * len(gt_labels)
        if len(gt_difficult) != len(gt_labels):
            raise ValueError(
                f"gt_difficult length {len(gt_difficult)} != "
                f"gt_labels length {len(gt_labels)}")
        for b, s, l in zip(det_boxes, det_scores, det_labels):
            self._dets.append((i, np.asarray(b), float(s), int(l)))
        for b, l, d in zip(gt_boxes, gt_labels, gt_difficult):
            self._gts.append((i, np.asarray(b), int(l), bool(d)))

    @staticmethod
    def _iou(a, b):
        lt = np.maximum(a[:2], b[:2])
        rb = np.minimum(a[2:], b[2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[0] * wh[1]
        ua = (a[2] - a[0]) * (a[3] - a[1]) + \
            (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / max(ua, 1e-10)

    def result(self) -> Dict[str, float]:
        aps = {}
        for c in range(1, self.num_classes):
            gts = [(i, b, d) for i, b, l, d in self._gts if l == c]
            dets = sorted([(i, b, s) for i, b, s, l in self._dets
                           if l == c], key=lambda t: -t[2])
            npos = sum(1 for _i, _b, d in gts if not d)
            if npos == 0:
                continue
            matched = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for d, (img, box, _s) in enumerate(dets):
                best, best_iou = None, self.iou_threshold
                for g, (gimg, gbox, _gd) in enumerate(gts):
                    if gimg != img or g in matched:
                        continue
                    iou = self._iou(box, gbox)
                    if iou >= best_iou:
                        best, best_iou = g, iou
                if best is not None:
                    if gts[best][2]:
                        # difficult match: ignore the detection entirely
                        continue
                    matched.add(best)
                    tp[d] = 1
                else:
                    fp[d] = 1
            ctp = np.cumsum(tp)
            cfp = np.cumsum(fp)
            recall = ctp / npos
            precision = ctp / np.maximum(ctp + cfp, 1e-10)
            aps[f"class_{c}"] = _voc_ap(recall, precision, self.use_07)
        mean = float(np.mean(list(aps.values()))) if aps else 0.0
        return {"mAP": mean, **aps}
