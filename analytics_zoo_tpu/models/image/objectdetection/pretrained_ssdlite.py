"""Pretrained SSDLite-320 / MobileNetV3-Large import.

The reference's detector zoo names ``ssd-mobilenet-300x300``
(ObjectDetectionConfig.scala:31-74) — the published descendant of that
recipe is torchvision's ``ssdlite320_mobilenet_v3_large`` COCO
checkpoint, imported here with the same playbook as the SSD300-VGG16
import (pretrained.py): the builder reproduces the SOURCE architecture
exactly so the weights are numerically faithful, and the import maps
checkpoint modules to layers BY NAME with loud mismatch errors.

Architecture notes (torchvision ssdlite.py + mobilenetv3.py):

* MobileNetV3-Large backbone with the REDUCED tail (the detection
  builder constructs it with ``reduced_tail=True``: the last three
  blocks halve to 80/480 channels) and detection BatchNorm
  (eps=1e-3 — our layer default).
* The C4 feature taps the EXPANSION conv inside block 13 (672 ch @
  20x20, MobileNetV3 paper §6.3); C5 is the 480-ch last conv @ 10x10.
* Four SSDLite extra blocks (1x1 → stride-2 depthwise 3x3 → 1x1, all
  Conv+BN+ReLU6) give 512@5, 256@3, 256@2, 128@1.
* Heads are SSDLite heads: depthwise 3x3 Conv+BN+ReLU6 then a biased
  1x1, 6 anchors per cell at every scale.
* Anchors: DefaultBoxGenerator(aspect_ratios=[[2,3]]*6) with scales
  derived from min_ratio=0.2 / max_ratio=0.95 and grid-normalized
  shifts (steps=None).
* Every stride-2 conv uses explicit torch-aligned padding
  (ZeroPadding2D + valid): XLA's SAME pads asymmetrically on even
  inputs, which would silently sample different pixels.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import ChainedPreprocessing
from analytics_zoo_tpu.feature.image import (
    ImageChannelNormalize, ImageResize)
from analytics_zoo_tpu.models.image.common import ImageConfigure
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, BatchNormalization, Convolution2D,
    GlobalAveragePooling2D, Lambda, Merge, Reshape, ZeroPadding2D,
)

# MobileNetV3-Large inverted-residual plan, REDUCED tail (the
# detection builder's reduced_tail=True halves the last stage):
# (kernel, expanded, out, use_se, activation, stride)
_MBV3_LARGE_REDUCED = (
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),      # C1
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),       # C2
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hard_swish", 2),   # C3
    (3, 200, 80, False, "hard_swish", 1),
    (3, 184, 80, False, "hard_swish", 1),
    (3, 184, 80, False, "hard_swish", 1),
    (3, 480, 112, True, "hard_swish", 1),
    (3, 672, 112, True, "hard_swish", 1),
    (5, 672, 80, True, "hard_swish", 2),    # C4 (reduced: 160//2)
    (5, 480, 80, True, "hard_swish", 1),
    (5, 480, 80, True, "hard_swish", 1),
)
_C4_BLOCK = 12            # index into the plan above (0-based)
_LAST_CONV = 480          # 6 * 80 (reduced tail)

_SSDLITE_FMAPS = (20, 10, 5, 3, 2, 1)
_SSDLITE_CHANNELS = (672, 480, 512, 256, 256, 128)
_SSDLITE_ANCHORS = 6      # 2 + 2*len([2, 3]) per cell, every scale


def _make_divisible(v, divisor: int = 8):
    """torchvision _make_divisible (channel rounding)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def ssdlite320_mobilenet_v3(num_classes: int = 91
                            ) -> Tuple[Model, np.ndarray, Dict[str, str]]:
    """Build the torchvision-exact SSDLite320 graph (NHWC).

    Returns (model, priors, name_map) — ``name_map`` maps each
    weight-bearing layer name to its checkpoint module prefix and is
    what ``load_torch_ssdlite320`` installs through."""
    slots: Dict[str, str] = {}
    ctr = itertools.count()

    def cna(x, f, k, prefix, stride=1, groups=1, act="hard_swish"):
        """Conv2dNormActivation: conv(bias=False) + BN(eps 1e-3) +
        activation, torch-aligned padding under stride 2."""
        name = f"sl{next(ctr):03d}"
        border = "same"
        if stride > 1 and k > 1:
            p = (k - 1) // 2
            x = ZeroPadding2D((p, p), name=name + "_pad")(x)
            border = "valid"
        x = Convolution2D(f, k, k, subsample=(stride, stride),
                          border_mode=border, bias=False, groups=groups,
                          name=name)(x)
        slots[name] = prefix + ".0"
        x = BatchNormalization(name=name + "_bn")(x)   # eps 1e-3 default
        slots[name + "_bn"] = prefix + ".1"
        if act:
            x = Activation(act, name=name + "_act")(x)
        return x

    def se_block(x, channels, prefix):
        """SqueezeExcitation: gap → fc1(relu) → fc2(hardsigmoid) →
        channel scale.  fc1/fc2 are biased 1x1 convs in the
        checkpoint."""
        name = f"sl{next(ctr):03d}"
        sq = _make_divisible(channels // 4)
        s = GlobalAveragePooling2D(name=name + "_gap")(x)
        s = Reshape((1, 1, channels), name=name + "_rs")(s)
        s = Convolution2D(sq, 1, 1, bias=True, activation="relu",
                          name=name + "_fc1")(s)
        slots[name + "_fc1"] = prefix + ".fc1"
        s = Convolution2D(channels, 1, 1, bias=True,
                          activation="hard_sigmoid_torch",
                          name=name + "_fc2")(s)
        slots[name + "_fc2"] = prefix + ".fc2"
        return Merge(mode="mul", name=name + "_scale")([x, s])

    def inverted_residual(x, in_ch, cfg, prefix):
        """torchvision InvertedResidual: [expand] → depthwise → [SE] →
        project, residual when stride 1 and in == out."""
        k, exp, out, use_se, act, stride = cfg
        h = x
        j = 0
        if exp != in_ch:
            h = cna(h, exp, 1, f"{prefix}.block.{j}", act=act)
            j += 1
        h = cna(h, exp, k, f"{prefix}.block.{j}", stride=stride,
                groups=exp, act=act)
        j += 1
        if use_se:
            h = se_block(h, exp, f"{prefix}.block.{j}")
            j += 1
        h = cna(h, out, 1, f"{prefix}.block.{j}", act=None)
        if stride == 1 and in_ch == out:
            h = Merge(mode="sum")([h, x])
        return h

    inp = Input(shape=(320, 320, 3), name="ssdlite_input")
    # ---- features.0: stem + blocks 0..11 + block 12's EXPAND conv
    x = cna(inp, 16, 3, "backbone.features.0.0", stride=2)   # 160
    in_ch = 16
    for i, cfg in enumerate(_MBV3_LARGE_REDUCED[:_C4_BLOCK]):
        x = inverted_residual(x, in_ch, cfg,
                              f"backbone.features.0.{i + 1}")
        in_ch = cfg[2]
    # block 12 split at its expansion (the C4 tap, paper §6.3): the
    # expand conv is features.0's LAST member…
    k, exp, out, use_se, act, stride = _MBV3_LARGE_REDUCED[_C4_BLOCK]
    c4 = cna(x, exp, 1, f"backbone.features.0.{_C4_BLOCK + 1}",
             act=act)                                         # 672@20
    # …and the rest of block 12 opens features.1 as ONE nested module:
    # torchvision slices the block (``backbone[c4_pos].block[1:]``)
    # and nn.Sequential slicing PRESERVES child names — so the
    # depthwise/SE/project live at features.1.0.{1,2,3}, not
    # re-indexed from 0
    h = cna(c4, exp, k, "backbone.features.1.0.1", stride=stride,
            groups=exp, act=act)                              # 10x10
    h = se_block(h, exp, "backbone.features.1.0.2")
    h = cna(h, out, 1, "backbone.features.1.0.3", act=None)
    in_ch = out
    for i, cfg in enumerate(_MBV3_LARGE_REDUCED[_C4_BLOCK + 1:]):
        h = inverted_residual(h, in_ch, cfg,
                              f"backbone.features.1.{i + 1}")
        in_ch = cfg[2]
    c5 = cna(h, _LAST_CONV, 1,
             f"backbone.features.1.{len(_MBV3_LARGE_REDUCED) - _C4_BLOCK}")

    # ---- SSDLite extras: 1x1 → s2 depthwise → 1x1 (all +BN+ReLU6)
    feats = [c4, c5]
    for i, out_ch in enumerate(_SSDLITE_CHANNELS[2:]):
        mid = out_ch // 2
        e = cna(feats[-1], mid, 1, f"backbone.extra.{i}.0", act="relu6")
        e = cna(e, mid, 3, f"backbone.extra.{i}.1", stride=2,
                groups=mid, act="relu6")
        e = cna(e, out_ch, 1, f"backbone.extra.{i}.2", act="relu6")
        feats.append(e)

    # ---- SSDLite heads: dw 3x3 (+BN+ReLU6) then biased 1x1; channel
    # blocks anchor-major so the channels-last reshape to (B, HWA, K)
    # reproduces torchvision's view/permute ordering
    locs, confs = [], []
    for i, (f, ch) in enumerate(zip(feats, _SSDLITE_CHANNELS)):
        for head, k_cols, coll in (
                ("classification_head", num_classes, confs),
                ("regression_head", 4, locs)):
            prefix = f"head.{head}.module_list.{i}"
            y = cna(f, ch, 3, f"{prefix}.0", groups=ch, act="relu6")
            name = f"sl{next(ctr):03d}"
            y = Convolution2D(_SSDLITE_ANCHORS * k_cols, 1, 1,
                              bias=True, name=name)(y)
            slots[name] = f"{prefix}.1"
            coll.append(Lambda(
                lambda t, c=k_cols: t.reshape(t.shape[0], -1, c),
                name=name + "_flat")(y))
    loc = Merge(mode="concat", concat_axis=1, name="ssdlite_loc")(locs)
    conf = Merge(mode="concat", concat_axis=1,
                 name="ssdlite_conf")(confs)
    model = Model(inp, [loc, conf])
    # the map rides on the model so load-by-name callers that only
    # hold the built model can still import by name
    model._ssdlite_name_map = dict(slots)
    return model, ssdlite_default_boxes(), slots


def ssdlite_default_boxes() -> np.ndarray:
    """torchvision DefaultBoxGenerator for ssdlite320: aspect ratios
    [2, 3] at every scale, scales from min_ratio 0.2 / max_ratio 0.95
    (+1.0 for the geometric mean at the last level), steps=None so
    shifts normalize by the grid size.  Corner form for
    ``decode_boxes`` (variances 0.1/0.2 == BoxCoder 10,10,5,5)."""
    n = len(_SSDLITE_FMAPS)
    scales = [0.2 + (0.95 - 0.2) * k / (n - 1.0) for k in range(n)]
    scales.append(1.0)
    out = []
    for k, fk in enumerate(_SSDLITE_FMAPS):
        s_k = scales[k]
        s_pk = math.sqrt(s_k * scales[k + 1])
        wh = [[s_k, s_k], [s_pk, s_pk]]
        for ar in (2.0, 3.0):
            sq = math.sqrt(ar)
            wh.append([s_k * sq, s_k / sq])
            wh.append([s_k / sq, s_k * sq])
        wh = np.clip(np.asarray(wh, np.float32), 0.0, 1.0)
        shifts = (np.arange(fk, dtype=np.float32) + 0.5) / fk
        sy, sx = np.meshgrid(shifts, shifts, indexing="ij")
        centers = np.stack([sx.reshape(-1), sy.reshape(-1)], -1)
        cxcy = np.repeat(centers, len(wh), axis=0)
        whs = np.tile(wh, (fk * fk, 1))
        out.append(np.concatenate(
            [cxcy - whs / 2, cxcy + whs / 2], axis=1))
    return np.concatenate(out, axis=0)


def load_torch_ssdlite320(model: Model, state_dict,
                          name_map: Dict[str, str] = None) -> None:
    """Import a torchvision ``ssdlite320_mobilenet_v3_large``
    state_dict into a ``ssdlite320_mobilenet_v3()`` model in place
    (name-mapped; loud on any mismatch).  ``name_map`` defaults to the
    map the builder stamped on the model.  All BNs carry the detection
    norm-layer epsilon 1e-3 — same as the layers' default, so the eps
    fold is the identity."""
    from analytics_zoo_tpu.models.image.objectdetection.pretrained \
        import install_by_name
    if name_map is None:
        name_map = getattr(model, "_ssdlite_name_map", None)
        if name_map is None:
            raise ValueError(
                "no name_map given and the model carries none — was "
                "it built by ssdlite320_mobilenet_v3()?")
    inner = state_dict.get("state_dict") \
        if isinstance(state_dict, dict) else None
    if isinstance(inner, dict):
        state_dict = inner
    install_by_name(model, dict(state_dict), name_map, bn_eps=1e-3)


def ssdlite_configure() -> ImageConfigure:
    """torchvision ssdlite transform: fixed 320x320 resize,
    mean/std 0.5 — in the 0-255 domain, x/127.5 - 1."""
    return ImageConfigure(
        preprocessor=ChainedPreprocessing([
            ImageResize(320, 320),
            ImageChannelNormalize(127.5, 127.5, 127.5,
                                  127.5, 127.5, 127.5)]),
        batch_per_partition=2)
