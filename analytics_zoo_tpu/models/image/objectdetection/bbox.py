"""Box utilities (ref: objectdetection/common/BboxUtil.scala, 1033 LoC
of per-box Scala loops — redesigned as fixed-shape vectorized jnp so
everything jits and runs on the VPU).

Boxes are (x1, y1, x2, y2) in [0, 1]; priors are center-form encoded
with SSD variances.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

VARIANCES = (0.1, 0.1, 0.2, 0.2)


def corner_to_center(boxes):
    wh = boxes[..., 2:] - boxes[..., :2]
    c = boxes[..., :2] + wh / 2
    return jnp.concatenate([c, wh], axis=-1)


def center_to_corner(boxes):
    c, wh = boxes[..., :2], boxes[..., 2:]
    return jnp.concatenate([c - wh / 2, c + wh / 2], axis=-1)


def iou_matrix(a, b):
    """a: (N,4), b: (M,4) corner boxes -> (N,M) IoU."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0, None) * \
        jnp.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0, None) * \
        jnp.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def encode_boxes(matched, priors, variances=VARIANCES):
    """Encode matched gt corner boxes against center-form priors
    (BboxUtil.encodeBoxes)."""
    m = corner_to_center(matched)
    p = corner_to_center(priors)
    g_c = (m[..., :2] - p[..., :2]) / (p[..., 2:] * variances[0])
    g_wh = jnp.log(jnp.maximum(m[..., 2:] / jnp.maximum(p[..., 2:], 1e-10),
                               1e-10)) / variances[2]
    return jnp.concatenate([g_c, g_wh], axis=-1)


def decode_boxes(loc, priors, variances=VARIANCES):
    """Inverse of encode (BboxUtil.decodeBoxes)."""
    p = corner_to_center(priors)
    c = p[..., :2] + loc[..., :2] * variances[0] * p[..., 2:]
    wh = p[..., 2:] * jnp.exp(loc[..., 2:] * variances[2])
    return jnp.clip(center_to_corner(
        jnp.concatenate([c, wh], axis=-1)), 0.0, 1.0)
