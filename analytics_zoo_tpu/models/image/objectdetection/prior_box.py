"""SSD prior (anchor) box generation — host-side, static
(ref: the PriorBox layer wiring in ssd/SSDGraph.scala)."""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


def priors_for_layer(fmap_size: int, image_size: int, min_size: float,
                     max_size: Optional[float],
                     aspect_ratios: Sequence[float]) -> np.ndarray:
    """Corner-form priors (fmap*fmap*k, 4) for one feature map."""
    boxes = []
    step = image_size / fmap_size
    for i in range(fmap_size):
        for j in range(fmap_size):
            cx = (j + 0.5) * step / image_size
            cy = (i + 0.5) * step / image_size
            s = min_size / image_size
            boxes.append([cx, cy, s, s])
            if max_size is not None:
                sp = math.sqrt(min_size * max_size) / image_size
                boxes.append([cx, cy, sp, sp])
            for ar in aspect_ratios:
                if ar == 1.0:
                    continue
                r = math.sqrt(ar)
                boxes.append([cx, cy, s * r, s / r])
                boxes.append([cx, cy, s / r, s * r])
    arr = np.asarray(boxes, np.float32)
    corner = np.concatenate(
        [arr[:, :2] - arr[:, 2:] / 2, arr[:, :2] + arr[:, 2:] / 2], axis=1)
    return np.clip(corner, 0.0, 1.0)


def num_priors_per_cell(max_size: Optional[float],
                        aspect_ratios: Sequence[float]) -> int:
    k = 1 + (1 if max_size is not None else 0)
    k += 2 * sum(1 for ar in aspect_ratios if ar != 1.0)
    return k


def ssd_priors(image_size: int, fmap_sizes: Sequence[int],
               min_sizes: Sequence[float],
               max_sizes: Sequence[Optional[float]],
               aspect_ratios: Sequence[Sequence[float]]) -> np.ndarray:
    """Stack priors over all feature maps -> (P, 4)."""
    parts = [priors_for_layer(f, image_size, mn, mx, ars)
             for f, mn, mx, ars in zip(fmap_sizes, min_sizes, max_sizes,
                                       aspect_ratios)]
    return np.concatenate(parts, axis=0)
