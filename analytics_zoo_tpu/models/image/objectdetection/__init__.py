from analytics_zoo_tpu.models.image.objectdetection.bbox import (
    decode_boxes, encode_boxes, iou_matrix,
)
from analytics_zoo_tpu.models.image.objectdetection.nms import (
    multiclass_nms, nms,
)
from analytics_zoo_tpu.models.image.objectdetection.prior_box import (
    ssd_priors,
)
from analytics_zoo_tpu.models.image.objectdetection.multibox_loss import (
    MultiBoxLoss, match_priors,
)
from analytics_zoo_tpu.models.image.objectdetection.ssd import (
    SSDDetector, ssd_lite, ssd_vgg300,
)
from analytics_zoo_tpu.models.image.objectdetection.evaluation import (
    MeanAveragePrecision,
)
from analytics_zoo_tpu.models.image.objectdetection.detector import (
    ObjectDetector,
)
from analytics_zoo_tpu.models.image.objectdetection.pretrained import (
    COCO_91_LABELS, coco_label_map, detection_configure,
    load_object_detector, load_torch_ssd300, ssd300_vgg16,
    tv_default_boxes,
)
from analytics_zoo_tpu.models.image.objectdetection.pretrained_ssdlite import (
    load_torch_ssdlite320, ssdlite320_mobilenet_v3,
    ssdlite_default_boxes,
)

__all__ = [
    "decode_boxes", "encode_boxes", "iou_matrix", "nms", "ssd_priors",
    "MultiBoxLoss", "match_priors", "multiclass_nms",
    "SSDDetector", "ssd_lite",
    "ssd_vgg300", "MeanAveragePrecision", "ObjectDetector",
    "COCO_91_LABELS", "coco_label_map", "detection_configure",
    "load_object_detector", "load_torch_ssd300", "ssd300_vgg16",
    "tv_default_boxes", "load_torch_ssdlite320",
    "ssdlite320_mobilenet_v3", "ssdlite_default_boxes",
]
