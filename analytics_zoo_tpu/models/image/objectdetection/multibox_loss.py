"""MultiBox loss with prior matching + hard negative mining.

Reference: objectdetection/common/loss/MultiBoxLoss.scala:622 — match
ground truths to priors by IoU (plus forced best-prior-per-gt match),
smooth-L1 on encoded locations, cross-entropy on confidences with 3:1
hard-negative mining.

TPU redesign: fully vectorized, fixed shapes — ground truths are padded
to ``max_gt`` with a validity mask; negative mining uses a rank trick
(sort negatives by loss, keep rank < 3·num_pos) instead of dynamic
top-k — every step is one fused XLA program.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.image.objectdetection.bbox import (
    encode_boxes, iou_matrix,
)


def match_priors(gt_boxes, gt_labels, gt_mask, priors,
                 iou_threshold: float = 0.5):
    """One image: gt (G,4)/(G,)/(G,) padded; priors (P,4).

    Returns (loc_targets (P,4), cls_targets (P,) int32 with 0 =
    background).
    """
    iou = iou_matrix(gt_boxes, priors)           # (G, P)
    iou = jnp.where(gt_mask[:, None], iou, -1.0)
    best_gt_per_prior = jnp.argmax(iou, axis=0)      # (P,)
    best_iou_per_prior = jnp.max(iou, axis=0)
    # force-match: each gt claims its best prior
    best_prior_per_gt = jnp.argmax(iou, axis=1)      # (G,)
    forced = jnp.zeros(priors.shape[0], bool)
    forced = forced.at[best_prior_per_gt].set(gt_mask)
    gt_of_forced = jnp.zeros(priors.shape[0], jnp.int32)
    gt_of_forced = gt_of_forced.at[best_prior_per_gt].set(
        jnp.arange(gt_boxes.shape[0], dtype=jnp.int32))

    assigned_gt = jnp.where(forced, gt_of_forced, best_gt_per_prior)
    positive = forced | (best_iou_per_prior >= iou_threshold)

    matched_boxes = gt_boxes[assigned_gt]
    matched_labels = gt_labels[assigned_gt].astype(jnp.int32)
    loc_targets = encode_boxes(matched_boxes, priors)
    cls_targets = jnp.where(positive, matched_labels, 0)
    return loc_targets, cls_targets


def smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


class MultiBoxLoss:
    """loss((gt_boxes, gt_labels, gt_mask), (loc_pred, conf_pred))."""

    def __init__(self, priors, neg_pos_ratio: float = 3.0,
                 iou_threshold: float = 0.5):
        self.priors = jnp.asarray(priors)
        self.neg_pos_ratio = float(neg_pos_ratio)
        self.iou_threshold = float(iou_threshold)
        self.name = "multibox_loss"

    def __call__(self, y_true, y_pred):
        gt_boxes, gt_labels, gt_mask = y_true
        loc_pred, conf_pred = y_pred        # (B,P,4), (B,P,C)

        loc_t, cls_t = jax.vmap(
            functools.partial(match_priors, priors=self.priors,
                              iou_threshold=self.iou_threshold)
        )(gt_boxes, gt_labels, gt_mask.astype(bool))

        positive = cls_t > 0                           # (B,P)
        num_pos = jnp.sum(positive, axis=1)            # (B,)

        # localisation: smooth-L1 on positives
        loc_loss = jnp.sum(smooth_l1(loc_pred - loc_t), axis=-1)
        loc_loss = jnp.sum(loc_loss * positive, axis=1)

        # confidence: CE everywhere, then hard-negative mining
        logp = jax.nn.log_softmax(conf_pred, axis=-1)
        ce = -jnp.take_along_axis(logp, cls_t[..., None],
                                  axis=-1)[..., 0]    # (B,P)
        neg_ce = jnp.where(positive, -jnp.inf, ce)
        # rank of each negative by descending loss
        order = jnp.argsort(-neg_ce, axis=1)
        rank = jnp.argsort(order, axis=1)
        max_neg = jnp.minimum(self.neg_pos_ratio * num_pos,
                              positive.shape[1] - num_pos)
        negative = (rank < max_neg[:, None]) & ~positive & \
            jnp.isfinite(neg_ce)
        conf_loss = jnp.sum(ce * (positive | negative), axis=1)

        denom = jnp.maximum(num_pos.astype(jnp.float32), 1.0)
        return jnp.mean((loc_loss + conf_loss) / denom)
