"""Published-checkpoint import for the object-detection zoo.

The reference ships load-by-name pretrained detectors with per-model
preprocess configs
(zoo/models/image/objectdetection/ObjectDetectionConfig.scala:31-74 —
``ssd-vgg16-300x300`` and friends; ObjectDetector.scala ``loadModel``).
There is no analytics-zoo weight zoo for this framework, so the
equivalent user journey — "load a published SSD and detect" — is
served by importing the ecosystem's published detection checkpoint
directly: torchvision's ``ssd300_vgg16`` COCO ``state_dict``
(the closest published descendant of the original SSD-VGG recipe).

Everything here mirrors the round-4 classification playbook
(imageclassification/pretrained.py): the builder reproduces the SOURCE
architecture exactly — plain-VGG16 backbone (no BN), ceil-mode pool3,
3x3/s1 pool5, dilated fc6, a learned L2-rescale on conv4_3
(``NormalizeScale``), torchvision's extra blocks and head layout, its
DefaultBoxGenerator anchors — so the imported weights are numerically
faithful, with the stride-2 extras using explicit torch-aligned
padding (ZeroPadding2D + valid) where XLA's SAME would pad
asymmetrically.  The import maps checkpoint modules to layers BY NAME
(an explicit slot table, loud on any mismatch), not positionally:
the functional graph's topological layer order interleaves heads with
backbone stages, so positional mapping would be silently wrong.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import ChainedPreprocessing
from analytics_zoo_tpu.feature.image import (
    ImageChannelNormalize, ImageResize)
from analytics_zoo_tpu.models.image.common import ImageConfigure
from analytics_zoo_tpu.models.image.imageclassification.pretrained import (
    _install, _model_slots, _torch_groups)
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    AtrousConvolution2D, Convolution2D, Lambda, MaxPooling2D, Merge,
    NormalizeScale, ZeroPadding2D,
)

# torchvision ssd300_vgg16 anchor recipe (DefaultBoxGenerator args)
_TV_SSD300_ASPECTS = ((2.0,), (2.0, 3.0), (2.0, 3.0), (2.0, 3.0),
                      (2.0,), (2.0,))
_TV_SSD300_SCALES = (0.07, 0.15, 0.33, 0.51, 0.69, 0.87, 1.05)
_TV_SSD300_STEPS = (8, 16, 32, 64, 100, 300)
_TV_SSD300_FMAPS = (38, 19, 10, 5, 3, 1)
# anchors per cell: 2 (scale + geometric-mean scale) + 2 per aspect
_TV_SSD300_ANCHORS = tuple(2 + 2 * len(a) for a in _TV_SSD300_ASPECTS)

_NORM_LAYER_NAME = "tv_conv4_3_norm"


def _conv(x, f, k, name, stride=1, border="same", dilation=None):
    """VGG/extra conv: bias + relu, torch-aligned padding for
    stride 2 (SAME pads asymmetrically on even inputs)."""
    if stride > 1 and k > 1:
        p = (k - 1) // 2
        x = ZeroPadding2D((p, p), name=name + "_pad")(x)
        border = "valid"
    if dilation is not None:
        return AtrousConvolution2D(
            f, k, k, atrous_rate=(dilation, dilation), border_mode=border,
            activation="relu", name=name)(x)
    return Convolution2D(f, k, k, subsample=(stride, stride),
                         border_mode=border, activation="relu",
                         name=name)(x)


def ssd300_vgg16(num_classes: int = 91) -> Tuple[Model, np.ndarray]:
    """SSD300-VGG16 in torchvision's exact architecture (NHWC), for
    importing its published COCO checkpoint.  Returns (model, priors);
    the model outputs ``[loc (B,8732,4), conf (B,8732,C)]`` matching
    ``SSDDetector``'s contract.  ``num_classes`` includes background
    (torchvision COCO: 91)."""
    inp = Input(shape=(300, 300, 3), name="tv_ssd_input")
    # ---- VGG16 features, through conv4_3 (backbone.features.*)
    x = _conv(inp, 64, 3, "tv_conv1_1")
    x = _conv(x, 64, 3, "tv_conv1_2")
    x = MaxPooling2D(name="tv_pool1")(x)                   # 150
    x = _conv(x, 128, 3, "tv_conv2_1")
    x = _conv(x, 128, 3, "tv_conv2_2")
    x = MaxPooling2D(name="tv_pool2")(x)                   # 75
    x = _conv(x, 256, 3, "tv_conv3_1")
    x = _conv(x, 256, 3, "tv_conv3_2")
    x = _conv(x, 256, 3, "tv_conv3_3")
    # ceil_mode pool3: SAME k2/s2 on 75 pads one -inf row/col right,
    # reproducing torch's ceil_mode window over the valid elements
    x = MaxPooling2D(border_mode="same", name="tv_pool3")(x)  # 38
    x = _conv(x, 512, 3, "tv_conv4_1")
    x = _conv(x, 512, 3, "tv_conv4_2")
    c43 = _conv(x, 512, 3, "tv_conv4_3")
    # learned channel-L2 rescale (backbone.scale_weight, init 20)
    r38 = NormalizeScale(scale_init=20.0, name=_NORM_LAYER_NAME)(c43)
    # ---- extra.0: conv5 block + dilated fc6 + fc7
    x = MaxPooling2D(name="tv_pool4")(c43)                 # 19
    x = _conv(x, 512, 3, "tv_conv5_1")
    x = _conv(x, 512, 3, "tv_conv5_2")
    x = _conv(x, 512, 3, "tv_conv5_3")
    x = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                     border_mode="same", name="tv_pool5")(x)  # 19
    x = _conv(x, 1024, 3, "tv_fc6", dilation=6)
    f19 = _conv(x, 1024, 1, "tv_fc7")
    # ---- extra.1..4
    x = _conv(f19, 256, 1, "tv_extra1_1")
    f10 = _conv(x, 512, 3, "tv_extra1_2", stride=2)        # 10
    x = _conv(f10, 128, 1, "tv_extra2_1")
    f5 = _conv(x, 256, 3, "tv_extra2_2", stride=2)         # 5
    x = _conv(f5, 128, 1, "tv_extra3_1")
    f3 = _conv(x, 256, 3, "tv_extra3_2", border="valid")   # 3
    x = _conv(f3, 128, 1, "tv_extra4_1")
    f1 = _conv(x, 256, 3, "tv_extra4_2", border="valid")   # 1
    feats = [r38, f19, f10, f5, f3, f1]

    # ---- heads: 3x3/pad1 convs; channels are anchor-major (A, K)
    # blocks, so the channels-last reshape to (B, H*W*A, K) reproduces
    # torchvision's view/permute ordering exactly
    locs, confs = [], []
    for i, (f, a) in enumerate(zip(feats, _TV_SSD300_ANCHORS)):
        conf = Convolution2D(a * num_classes, 3, 3, border_mode="same",
                             name=f"tv_cls{i}")(f)
        loc = Convolution2D(a * 4, 3, 3, border_mode="same",
                            name=f"tv_reg{i}")(f)
        confs.append(Lambda(
            lambda t, c=num_classes: t.reshape(t.shape[0], -1, c),
            name=f"tv_cls{i}_flat")(conf))
        locs.append(Lambda(
            lambda t: t.reshape(t.shape[0], -1, 4),
            name=f"tv_reg{i}_flat")(loc))
    loc = Merge(mode="concat", concat_axis=1, name="tv_loc")(locs)
    conf = Merge(mode="concat", concat_axis=1, name="tv_conf")(confs)
    return Model(inp, [loc, conf]), tv_default_boxes()


def tv_default_boxes() -> np.ndarray:
    """torchvision ``DefaultBoxGenerator`` anchors for SSD300, in
    corner form (x1,y1,x2,y2), normalized — the prior layout
    ``decode_boxes`` consumes (its (0.1, 0.2) variances equal
    torchvision's BoxCoder weights (10, 10, 5, 5)).

    Per cell: [s_k, s_k], [s'_k, s'_k] (geometric mean), then
    [w, h], [h, w] per aspect ratio; w/h clamped to [0, 1] (clip=True)
    while centers stay raw; cell order row-major over (H, W)."""
    out = []
    for k, fk in enumerate(_TV_SSD300_FMAPS):
        s_k = _TV_SSD300_SCALES[k]
        s_pk = math.sqrt(s_k * _TV_SSD300_SCALES[k + 1])
        wh = [[s_k, s_k], [s_pk, s_pk]]
        for ar in _TV_SSD300_ASPECTS[k]:
            sq = math.sqrt(ar)
            wh.append([s_k * sq, s_k / sq])
            wh.append([s_k / sq, s_k * sq])
        wh = np.clip(np.array(wh, np.float32), 0.0, 1.0)    # clip=True
        f_img = 300.0 / _TV_SSD300_STEPS[k]
        shifts = (np.arange(fk, dtype=np.float32) + 0.5) / f_img
        sy, sx = np.meshgrid(shifts, shifts, indexing="ij")
        centers = np.stack([sx.reshape(-1), sy.reshape(-1)], -1)
        cxcy = np.repeat(centers, len(wh), axis=0)
        whs = np.tile(wh, (fk * fk, 1))
        out.append(np.concatenate(
            [cxcy - whs / 2, cxcy + whs / 2], axis=1))
    return np.concatenate(out, axis=0)


# checkpoint module prefix for each named layer (torchvision
# ssd300_vgg16 state_dict layout); VGG16 ``features`` conv indices are
# 0,2,5,7,10,12,14,17,19,21, ``extra.0`` holds conv5_* + fc6/fc7 at
# sequential indices 1,3,5,8,10, later extras at 0,2
_TV_SSD300_SLOTS: Dict[str, str] = {
    "tv_conv1_1": "backbone.features.0",
    "tv_conv1_2": "backbone.features.2",
    "tv_conv2_1": "backbone.features.5",
    "tv_conv2_2": "backbone.features.7",
    "tv_conv3_1": "backbone.features.10",
    "tv_conv3_2": "backbone.features.12",
    "tv_conv3_3": "backbone.features.14",
    "tv_conv4_1": "backbone.features.17",
    "tv_conv4_2": "backbone.features.19",
    "tv_conv4_3": "backbone.features.21",
    "tv_conv5_1": "backbone.extra.0.1",
    "tv_conv5_2": "backbone.extra.0.3",
    "tv_conv5_3": "backbone.extra.0.5",
    "tv_fc6": "backbone.extra.0.8",
    "tv_fc7": "backbone.extra.0.10",
    "tv_extra1_1": "backbone.extra.1.0",
    "tv_extra1_2": "backbone.extra.1.2",
    "tv_extra2_1": "backbone.extra.2.0",
    "tv_extra2_2": "backbone.extra.2.2",
    "tv_extra3_1": "backbone.extra.3.0",
    "tv_extra3_2": "backbone.extra.3.2",
    "tv_extra4_1": "backbone.extra.4.0",
    "tv_extra4_2": "backbone.extra.4.2",
    **{f"tv_cls{i}": f"head.classification_head.module_list.{i}"
       for i in range(6)},
    **{f"tv_reg{i}": f"head.regression_head.module_list.{i}"
       for i in range(6)},
}


def install_by_name(model: Model, state_dict, name_map: Dict[str, str],
                    bn_eps: float = 1e-5) -> None:
    """Install a torch ``state_dict`` through an explicit layer-name →
    checkpoint-module-prefix table.

    The functional graph's topological layer order interleaves heads
    with backbone stages, so POSITIONAL mapping (the classification
    importer's contract) would be silently wrong here; name mapping
    raises with the offender named on any mismatch instead."""
    groups = _torch_groups(state_dict, bn_eps=bn_eps)
    by_prefix = {g["__name__"]: (kind, g) for kind, g in groups}
    slots = _model_slots(model)
    ordered = []
    for kind, layer in slots:
        prefix = name_map.get(layer.name)
        if prefix is None:
            raise ValueError(
                f"model layer {layer.name!r} has no checkpoint mapping "
                "— was this model built by the matching builder?")
        entry = by_prefix.pop(prefix, None)
        if entry is None:
            raise ValueError(
                f"checkpoint module {prefix!r} (for layer "
                f"{layer.name!r}) missing from the state_dict")
        ordered.append(entry)
    if by_prefix:
        raise ValueError(
            "checkpoint modules with no model layer: "
            f"{sorted(by_prefix)}")
    _install(model, ordered)


def load_torch_ssd300(model: Model, state_dict) -> None:
    """Import a torchvision ``ssd300_vgg16`` state_dict into a
    ``ssd300_vgg16()`` model in place.

    ``backbone.scale_weight`` (a bare parameter, not a module) lands on
    the NormalizeScale layer; every conv maps through the explicit
    name table — unknown checkpoint modules or unmapped layers raise
    with the offender named."""
    inner = state_dict.get("state_dict") \
        if isinstance(state_dict, dict) else None
    if isinstance(inner, dict):
        state_dict = inner
    sd = dict(state_dict)
    scale = sd.pop("backbone.scale_weight", None)
    if scale is None:
        raise ValueError(
            "checkpoint has no 'backbone.scale_weight' — not a "
            "torchvision ssd300_vgg16 state_dict")
    if hasattr(scale, "detach"):
        scale = scale.detach().cpu().numpy()
    scale = np.asarray(scale)

    install_by_name(model, sd, _TV_SSD300_SLOTS)

    variables = model.get_variables()
    cur = variables["params"][_NORM_LAYER_NAME]["scale"]
    if tuple(np.shape(scale)) != tuple(np.shape(cur)):
        raise ValueError(
            f"backbone.scale_weight shape {tuple(scale.shape)} != "
            f"NormalizeScale scale {tuple(np.shape(cur))}")
    variables["params"][_NORM_LAYER_NAME]["scale"] = \
        scale.astype(np.asarray(cur).dtype)
    model.set_variables(variables)


# torchvision ssd300_vgg16 emits raw COCO category ids in the
# paper's 91-slot space (11 unused slots, marked N/A) — the label
# vocabulary of the published checkpoint (LabelReader("coco") role;
# ref ships zoo/src/main/resources/coco_classname.txt for its 80-class
# variant)
COCO_91_LABELS = (
    "__background__", "person", "bicycle", "car", "motorcycle",
    "airplane", "bus", "train", "truck", "boat", "traffic light",
    "fire hydrant", "N/A", "stop sign", "parking meter", "bench",
    "bird", "cat", "dog", "horse", "sheep", "cow", "elephant", "bear",
    "zebra", "giraffe", "N/A", "backpack", "umbrella", "N/A", "N/A",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove",
    "skateboard", "surfboard", "tennis racket", "bottle", "N/A",
    "wine glass", "cup", "fork", "knife", "spoon", "bowl", "banana",
    "apple", "sandwich", "orange", "broccoli", "carrot", "hot dog",
    "pizza", "donut", "cake", "chair", "couch", "potted plant", "bed",
    "N/A", "dining table", "N/A", "N/A", "toilet", "N/A", "tv",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "N/A", "book", "clock",
    "vase", "scissors", "teddy bear", "hair drier", "toothbrush",
)


def coco_label_map() -> Dict[str, int]:
    """name -> 91-space category id (N/A slots excluded)."""
    return {n: i for i, n in enumerate(COCO_91_LABELS) if n != "N/A"}


def load_object_detector(name: str = "ssd300-vgg16-coco",
                         checkpoint=None,
                         score_threshold: float = 0.3,
                         iou_threshold: float = 0.45,
                         max_detections: int = 100,
                         per_class_nms: bool = True,
                         topk_per_class: int = 400):
    """Load-by-name pretrained detector — the
    ``ObjectDetector.loadModel(name)`` journey
    (ObjectDetectionConfig.scala:31-74).

    ``checkpoint``: a torchvision ``ssd300_vgg16`` state_dict, or a
    ``.pth`` path to one.  This environment has no network egress, so
    the published weights can't be fetched here — download
    ``ssd300_vgg16_coco-b556d3b4.pth`` from torchvision's model zoo
    and pass its path.

    ``per_class_nms=True`` by default: the published COCO detector's
    postprocess is per-class NMS with cross-class results (torchvision
    semantics) — best-class-only NMS would merge overlapping objects
    of different classes."""
    from analytics_zoo_tpu.models.image.objectdetection.detector import (
        ObjectDetector)
    names = {
        # name -> (model_type, input size, published .pth file)
        "ssd300-vgg16-coco": (
            "ssd300_vgg16", 300, "ssd300_vgg16_coco-b556d3b4.pth"),
        "ssdlite320-mobilenet-v3-coco": (
            "ssdlite320_mobilenet_v3", 320,
            "ssdlite320_mobilenet_v3_large_coco-a79551df.pth"),
    }
    if name not in names:
        raise ValueError(
            f"unknown pretrained detector {name!r} "
            f"(have: {', '.join(sorted(names))})")
    model_type, size, pth = names[name]
    if checkpoint is None:
        raise ValueError(
            f"checkpoint required: pass a torchvision {model_type} "
            f"state_dict or a .pth path (e.g. {pth} from the "
            "torchvision model zoo; this environment cannot "
            "download it)")
    det = ObjectDetector(
        model_type=model_type, num_classes=len(COCO_91_LABELS),
        image_size=size, score_threshold=score_threshold,
        iou_threshold=iou_threshold, max_detections=max_detections,
        per_class_nms=per_class_nms, topk_per_class=topk_per_class,
        label_map=coco_label_map())
    if isinstance(checkpoint, (str, bytes)) or hasattr(checkpoint,
                                                       "__fspath__"):
        import torch
        checkpoint = torch.load(checkpoint, map_location="cpu",
                                weights_only=True)
    if model_type == "ssd300_vgg16":
        load_torch_ssd300(det.model, checkpoint)
    else:
        from analytics_zoo_tpu.models.image.objectdetection \
            .pretrained_ssdlite import load_torch_ssdlite320
        # name_map defaults to the map the builder stamped on the model
        load_torch_ssdlite320(det.model, checkpoint)
    cfg = detection_configure(name)
    det.config = ImageConfigure(
        preprocessor=cfg.preprocessor,
        batch_per_partition=cfg.batch_per_partition,
        label_map=coco_label_map())
    return det


def detection_configure(model_name: str = "ssd300-vgg16-coco"
                        ) -> ImageConfigure:
    """Preprocess matching the published detector's training transform
    (the per-name configure table of ObjectDetectionConfig.scala:31-74,
    in the 0-255 pixel domain the ImageSet pipeline produces).

    torchvision's SSD transform resizes to a fixed 300x300 and
    normalizes with mean [0.48235, 0.45882, 0.40784], std 1/255 —
    in the 0-255 domain that is mean subtraction only (the classic
    Caffe-lineage VGG means, RGB order); ssdlite normalizes 0-255 to
    [-1, 1] at 320x320 (see pretrained_ssdlite.ssdlite_configure)."""
    if model_name == "ssdlite320-mobilenet-v3-coco":
        from analytics_zoo_tpu.models.image.objectdetection \
            .pretrained_ssdlite import ssdlite_configure
        return ssdlite_configure()
    if model_name not in ("ssd300-vgg16-coco",):
        raise ValueError(
            f"unknown pretrained detector {model_name!r} "
            "(have: ssd300-vgg16-coco, ssdlite320-mobilenet-v3-coco)")
    return ImageConfigure(
        preprocessor=ChainedPreprocessing([
            ImageResize(300, 300),
            ImageChannelNormalize(0.48235 * 255, 0.45882 * 255,
                                  0.40784 * 255)]),
        batch_per_partition=2)
