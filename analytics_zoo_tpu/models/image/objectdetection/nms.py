"""Non-maximum suppression, jit-friendly (ref: the Nms class in
objectdetection/common — scalar loops there; here a fixed-iteration
select-and-suppress loop with static output size)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.image.objectdetection.bbox import iou_matrix


def nms(boxes, scores, iou_threshold: float = 0.45,
        max_output: int = 100, score_threshold: float = 0.0):
    """boxes (N,4), scores (N,) -> (idx (max_output,), valid mask).

    Greedy NMS as a lax.fori_loop with static shapes: each step picks
    the best remaining score and suppresses overlaps.  Padded slots
    return index -1.
    """
    n = boxes.shape[0]
    iou = iou_matrix(boxes, boxes)
    alive = scores > score_threshold

    def body(i, carry):
        alive, out_idx, out_valid = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        out_idx = out_idx.at[i].set(jnp.where(ok, best, -1))
        out_valid = out_valid.at[i].set(ok)
        suppress = iou[best] >= iou_threshold
        alive = alive & ~suppress & ~(jnp.arange(n) == best)
        alive = jnp.where(ok, alive, jnp.zeros_like(alive))
        return alive, out_idx, out_valid

    out_idx = jnp.full((max_output,), -1, jnp.int32)
    out_valid = jnp.zeros((max_output,), bool)
    _, out_idx, out_valid = jax.lax.fori_loop(
        0, max_output, body, (alive, out_idx, out_valid))
    return out_idx, out_valid


def multiclass_nms(boxes, probs, iou_threshold: float = 0.45,
                   score_threshold: float = 0.01,
                   topk_per_class: int = 400,
                   max_detections: int = 200):
    """Per-class NMS with cross-class results — torchvision SSD
    postprocess semantics (a location can be detected as SEVERAL
    classes; best-class-only NMS merges overlapping objects of
    different classes).

    ``boxes`` (P,4), ``probs`` (P,C) with class 0 = background.
    Per non-background class: top-``topk_per_class`` candidates by
    score (bounds the per-class IoU matrix to k², the reason
    torchvision has the same knob), greedy NMS, then the global
    top-``max_detections`` across classes by score.

    Returns (boxes (D,4), scores (D,), labels (D,) int32, valid (D,))
    with D = ``max_detections``; invalid slots carry label 0.
    """
    p, c = probs.shape
    k = min(topk_per_class, p)
    m = min(max_detections, k)

    def per_class(scores_c):
        top_scores, top_idx = jax.lax.top_k(scores_c, k)
        cand = boxes[top_idx]
        idx, valid = nms(cand, top_scores, iou_threshold, m,
                         score_threshold)
        safe = jnp.maximum(idx, 0)
        return (top_idx[safe], jnp.where(valid, top_scores[safe],
                                         -jnp.inf), valid)

    # (C-1, m) each; class axis vmapped so the k x k IoU work stays
    # bounded at (C-1) * k^2
    sel, sc, valid = jax.vmap(per_class)(probs[:, 1:].T)
    labels = jnp.broadcast_to(
        jnp.arange(1, c, dtype=jnp.int32)[:, None], sel.shape)

    flat_scores = sc.reshape(-1)
    # the candidate pool can be SMALLER than max_detections (few
    # classes / tiny prior sets): top_k requires k <= pool size, so
    # take what exists and pad the outputs up to D
    d = min(max_detections, flat_scores.shape[0])
    best_scores, order = jax.lax.top_k(flat_scores, d)
    out_valid = best_scores > -jnp.inf
    safe = jnp.maximum(order, 0)
    out_boxes = boxes[sel.reshape(-1)[safe]]
    out_labels = jnp.where(out_valid, labels.reshape(-1)[safe], 0)
    out_scores = jnp.where(out_valid, best_scores, 0.0)
    pad = max_detections - d
    if pad:
        out_boxes = jnp.concatenate(
            [out_boxes, jnp.zeros((pad, 4), out_boxes.dtype)])
        out_scores = jnp.concatenate(
            [out_scores, jnp.zeros((pad,), out_scores.dtype)])
        out_labels = jnp.concatenate(
            [out_labels, jnp.zeros((pad,), out_labels.dtype)])
        out_valid = jnp.concatenate(
            [out_valid, jnp.zeros((pad,), bool)])
    return out_boxes, out_scores, out_labels.astype(jnp.int32), out_valid
