"""Non-maximum suppression, jit-friendly (ref: the Nms class in
objectdetection/common — scalar loops there; here a fixed-iteration
select-and-suppress loop with static output size)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.image.objectdetection.bbox import iou_matrix


def nms(boxes, scores, iou_threshold: float = 0.45,
        max_output: int = 100, score_threshold: float = 0.0):
    """boxes (N,4), scores (N,) -> (idx (max_output,), valid mask).

    Greedy NMS as a lax.fori_loop with static shapes: each step picks
    the best remaining score and suppresses overlaps.  Padded slots
    return index -1.
    """
    n = boxes.shape[0]
    iou = iou_matrix(boxes, boxes)
    alive = scores > score_threshold

    def body(i, carry):
        alive, out_idx, out_valid = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        out_idx = out_idx.at[i].set(jnp.where(ok, best, -1))
        out_valid = out_valid.at[i].set(ok)
        suppress = iou[best] >= iou_threshold
        alive = alive & ~suppress & ~(jnp.arange(n) == best)
        alive = jnp.where(ok, alive, jnp.zeros_like(alive))
        return alive, out_idx, out_valid

    out_idx = jnp.full((max_output,), -1, jnp.int32)
    out_valid = jnp.zeros((max_output,), bool)
    _, out_idx, out_valid = jax.lax.fori_loop(
        0, max_output, body, (alive, out_idx, out_valid))
    return out_idx, out_valid
