"""ObjectDetector: the user-facing detection model facade.

Reference: zoo/models/image/objectdetection/ObjectDetector.scala —
``loadModel`` materialises a published detector by name/path, and
``predictImageSet`` runs the ImageConfigure preprocess → forward →
decode/NMS postprocess chain; ``Visualizer.scala`` draws the boxes.

TPU design: the detector is a ZooModel wrapping an SSD graph + priors;
the whole postprocess (box decode + per-class NMS) runs inside the
jitted program (SSDDetector).  ``save_model``/``load_model`` persist
architecture metadata + trained variables in one file, so a trained
detector is reloadable by path — the published-model-zoo role in a
zero-egress environment.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.image.common import (ImageConfigure,
                                                   ImageModel)
from analytics_zoo_tpu.models.image.objectdetection.ssd import (
    SSDDetector, ssd_lite, ssd_vgg300)


def _tv_ssd300_vgg16(num_classes: int = 91):
    # lazy: pretrained.py pulls in the classification import machinery
    from analytics_zoo_tpu.models.image.objectdetection.pretrained import (
        ssd300_vgg16)
    return ssd300_vgg16(num_classes=num_classes)


def _tv_ssdlite320(num_classes: int = 91):
    from analytics_zoo_tpu.models.image.objectdetection \
        .pretrained_ssdlite import ssdlite320_mobilenet_v3
    model, priors, _name_map = ssdlite320_mobilenet_v3(
        num_classes=num_classes)
    return model, priors


_ARCHS = {"ssd_lite": ssd_lite, "ssd_vgg300": ssd_vgg300,
          "ssd300_vgg16": _tv_ssd300_vgg16,
          "ssdlite320_mobilenet_v3": _tv_ssdlite320}
# architectures whose input size is baked into the graph
_FIXED_SIZE = {"ssd_vgg300": 300, "ssd300_vgg16": 300,
               "ssdlite320_mobilenet_v3": 320}


class ObjectDetector(ImageModel):
    """A named SSD architecture + trained weights + detection config."""

    def __init__(self, model_type: str = "ssd_lite",
                 num_classes: int = 21, image_size: int = 300,
                 score_threshold: float = 0.3,
                 iou_threshold: float = 0.45,
                 max_detections: int = 100,
                 per_class_nms: bool = False,
                 topk_per_class: int = 400,
                 label_map: Optional[Dict[str, int]] = None,
                 config: Optional[ImageConfigure] = None):
        if model_type not in _ARCHS:
            raise ValueError(f"unknown detector '{model_type}' "
                             f"(have {sorted(_ARCHS)})")
        self.model_type = model_type
        self.num_classes = int(num_classes)
        self.image_size = int(image_size)
        self.score_threshold = float(score_threshold)
        self.iou_threshold = float(iou_threshold)
        self.max_detections = int(max_detections)
        self.per_class_nms = bool(per_class_nms)
        self.topk_per_class = int(topk_per_class)
        self._detector = None
        self._detector_key = None
        super().__init__(config=config or ImageConfigure(
            label_map=label_map))

    # ------------------------------------------------------------ building
    def build_model(self):
        if self.model_type in _FIXED_SIZE:    # input size baked in
            self.image_size = _FIXED_SIZE[self.model_type]
            model, self.priors = _ARCHS[self.model_type](
                num_classes=self.num_classes)
        else:
            model, self.priors = _ARCHS[self.model_type](
                num_classes=self.num_classes, image_size=self.image_size)
        model.init()
        return model

    @property
    def detector(self) -> SSDDetector:
        # rebuild when a threshold changed — the jitted postprocess
        # bakes them in, so a stale cache would silently ignore edits
        key = (self.score_threshold, self.iou_threshold,
               self.max_detections, self.per_class_nms,
               self.topk_per_class)
        if self._detector is None or self._detector_key != key:
            self._detector = SSDDetector(
                self.model, self.priors, num_classes=self.num_classes,
                score_threshold=self.score_threshold,
                iou_threshold=self.iou_threshold,
                max_detections=self.max_detections,
                per_class_nms=self.per_class_nms,
                topk_per_class=self.topk_per_class)
            self._detector_key = key
        return self._detector

    # ----------------------------------------------------------- detection
    def detect(self, images: np.ndarray
               ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """-> per image (boxes (k,4) normalised, scores, labels)."""
        return self.detector.detect(np.asarray(images, np.float32))

    def predict_image_set(self, image_set, configure=None,
                          batch_size: int = 32):
        """ImageSet → per-image detections (predictImageSet role).

        Unlike classification, the postprocessor here is called PER
        IMAGE with a ``(boxes, scores, labels)`` tuple.
        """
        cfg = configure or self.config
        x = self._materialize_image_set(image_set, cfg)
        out = []
        for lo in range(0, len(x), batch_size):
            chunk = x[lo:lo + batch_size]
            real = len(chunk)
            if real < batch_size:    # pad: ONE compiled program serves
                chunk = np.concatenate(   # every tail size
                    [chunk, np.zeros((batch_size - real,)
                                     + chunk.shape[1:], chunk.dtype)])
            out.extend(self.detect(chunk)[:real])
        if cfg.postprocessor is not None:
            out = [cfg.postprocessor(o) for o in out]
        return out

    def label_names(self, labels: Sequence[int]) -> List[str]:
        if not self.config.label_map:
            return [str(int(l)) for l in labels]
        inv = {v: k for k, v in self.config.label_map.items()}
        return [inv.get(int(l), str(int(l))) for l in labels]

    # ------------------------------------------------------- visualisation
    def visualize(self, image: np.ndarray, boxes: np.ndarray,
                  scores: np.ndarray, labels: np.ndarray,
                  min_score: float = 0.0) -> np.ndarray:
        """Draw detections on an HWC image; returns a uint8 canvas
        (Visualizer.scala role)."""
        img = np.asarray(image)
        if img.dtype != np.uint8:    # drawing needs a uint8 canvas
            hi = float(img.max()) if img.size else 1.0
            # [0,1]-normalised floats scale up; 0..255 floats just clip
            # (a ratio-based stretch would distort appearance)
            if hi <= 1.0:
                img = img * 255.0
            img = np.clip(img, 0, 255).astype(np.uint8)
        img = np.ascontiguousarray(img)
        h, w = img.shape[:2]
        names = self.label_names(labels)
        try:
            import cv2
        except ImportError:          # pragma: no cover
            cv2 = None
        color = (0, 255, 0)
        for box, score, name in zip(boxes, scores, names):
            if score < min_score:
                continue
            x0 = min(max(int(box[0] * w), 0), w - 1)
            y0 = min(max(int(box[1] * h), 0), h - 1)
            x1 = min(max(int(box[2] * w), 0), w - 1)
            y1 = min(max(int(box[3] * h), 0), h - 1)
            if cv2 is not None:
                cv2.rectangle(img, (x0, y0), (x1, y1), color, 1)
                cv2.putText(img, f"{name}:{score:.2f}", (x0, max(y0, 10)),
                            cv2.FONT_HERSHEY_PLAIN, 0.8, color)
            else:                    # pragma: no cover
                img[y0:y1 + 1, x0] = color
                img[y0:y1 + 1, x1] = color
                img[y0, x0:x1 + 1] = color
                img[y1, x0:x1 + 1] = color
        return img

    # --------------------------------------------------------- persistence
    def save_model(self, path: str, over_write: bool = True) -> None:
        """One-file persistence: architecture meta + trained variables
        (ObjectDetector.loadModel's artifact format).  The payload is a
        flax-msgpack pytree — NO pickle, so loading an artifact from an
        untrusted source cannot execute code — written atomically with
        remote-path support (utils/serialization.save_variables)."""
        import jax

        from analytics_zoo_tpu.utils.serialization import save_variables
        variables = jax.tree_util.tree_map(
            np.asarray, self.model.get_variables())
        # auto-names (dense_7...) depend on process history; key the
        # saved tree by the model's deterministic LAYER ORDER instead
        # so any process can reload it
        order = [l.name for l in self.model.layers]
        if len(set(order)) != len(order):
            dupes = sorted({n for n in order if order.count(n) > 1})
            raise ValueError(
                f"duplicate layer names {dupes}: order-keyed save would "
                "silently overwrite one layer's weights with another's")
        index_of = {n: i for i, n in enumerate(order)}
        variables = {
            kind: {f"layer_{index_of[n]:04d}": sub
                   for n, sub in tree.items()}
            for kind, tree in variables.items()}
        meta = {
            "model_type": self.model_type,
            "num_classes": self.num_classes,
            "image_size": self.image_size,
            "score_threshold": self.score_threshold,
            "iou_threshold": self.iou_threshold,
            "max_detections": self.max_detections,
            "per_class_nms": self.per_class_nms,
            "topk_per_class": self.topk_per_class,
            "label_map": self.config.label_map,
        }
        save_variables(path, {
            "format": "zoo_object_detector_v1",
            "meta": json.dumps(meta),
            "variables": variables,
        }, over_write=over_write)

    @classmethod
    def load_model(cls, path: str) -> "ObjectDetector":
        import jax
        from flax import serialization as fser

        from analytics_zoo_tpu.utils import file_io
        payload = fser.msgpack_restore(file_io.read_bytes(path))
        if payload.get("format") != "zoo_object_detector_v1":
            raise ValueError(f"{path} is not a saved ObjectDetector")
        meta = json.loads(payload["meta"])
        label_map = meta.pop("label_map", None)
        det = cls(label_map=label_map, **meta)
        like = det.model.get_variables()
        order = [l.name for l in det.model.layers]
        indices = [int(key.split("_")[-1])
                   for tree in payload["variables"].values()
                   for key in tree]
        if any(i >= len(order) for i in indices):
            raise ValueError(
                f"{path}: saved detector does not match the rebuilt "
                f"{meta['model_type']} architecture (extra layers)")
        restored = {
            kind: {order[int(key.split("_")[-1])]: sub
                   for key, sub in tree.items()}
            for kind, tree in payload["variables"].items()}
        s_leaves = jax.tree_util.tree_leaves(restored)
        l_leaves = jax.tree_util.tree_leaves(like)
        if len(s_leaves) != len(l_leaves) or any(
                np.shape(a) != np.shape(b)
                for a, b in zip(s_leaves, l_leaves)):
            raise ValueError(
                f"{path}: saved detector does not match the rebuilt "
                f"{meta['model_type']} architecture")
        det.model.set_variables(restored)
        return det
