"""ImageModel base + per-model ImageConfigure.

Reference: zoo/models/image/common/ImageModel.scala:47 (predictImageSet
dispatching through a model-specific ``ImageConfigure``) and
ImageConfigure.scala:88 (preprocessor, postprocessor, batch size, label
map).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.models.common import ZooModel


@dataclasses.dataclass
class ImageConfigure:
    preprocessor: Optional[Preprocessing] = None
    postprocessor: Optional[Callable] = None
    batch_per_partition: int = 4
    label_map: Optional[dict] = None


class ImageModel(ZooModel):
    """Base for image classification / detection models."""

    def __init__(self, config: Optional[ImageConfigure] = None):
        self.config = config or ImageConfigure()
        super().__init__()

    def _materialize_image_set(self, image_set, cfg: ImageConfigure
                               ) -> np.ndarray:
        """Shared preprocess → stacked batch step of predictImageSet."""
        if cfg.preprocessor is not None:
            image_set = image_set.transform(cfg.preprocessor)
        return np.stack(image_set.images).astype(np.float32)

    def predict_image_set(self, image_set, configure: Optional[
            ImageConfigure] = None, batch_size: int = 32):
        cfg = configure or self.config
        x = self._materialize_image_set(image_set, cfg)
        out = self.predict(x, batch_size=batch_size)
        if cfg.postprocessor is not None:
            out = cfg.postprocessor(out)
        return out

    def predict_image_classes(self, image_set, top_k: int = 1, **kwargs):
        out = np.asarray(self.predict_image_set(image_set, **kwargs))
        idx = np.argsort(-out, axis=-1)[:, :top_k]
        if self.config.label_map:
            inv = {v: k for k, v in self.config.label_map.items()}
            return [[inv.get(int(i), int(i)) for i in row] for row in idx]
        return idx
