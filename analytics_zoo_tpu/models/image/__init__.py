from analytics_zoo_tpu.models.image.common import ImageConfigure, ImageModel
