from analytics_zoo_tpu.models.image.imageclassification.nets import (
    ImageClassifier, alexnet, densenet, inception_v1, lenet, mobilenet,
    resnet, squeezenet, vgg,
)
from analytics_zoo_tpu.models.image.imageclassification.pretrained import (
    load_pretrained, pretrained_configure,
)

__all__ = ["ImageClassifier", "alexnet", "densenet", "inception_v1",
           "lenet", "load_pretrained", "mobilenet", "pretrained_configure",
           "resnet", "squeezenet", "vgg"]
