from analytics_zoo_tpu.models.image.imageclassification.nets import (
    ImageClassifier, alexnet, densenet, inception_v1, lenet, mobilenet,
    resnet, squeezenet, vgg,
)

__all__ = ["ImageClassifier", "alexnet", "densenet", "inception_v1",
           "lenet", "mobilenet", "resnet", "squeezenet", "vgg"]
