from analytics_zoo_tpu.models.image.imageclassification.nets import (
    ImageClassifier, inception_v1, lenet, resnet,
)

__all__ = ["ImageClassifier", "inception_v1", "lenet", "resnet"]
