"""Published-checkpoint import for the image model zoo.

The reference ships load-by-name pretrained models with per-model
preprocess configs
(zoo/models/image/imageclassification/ImageClassificationConfig.scala:190,
zoo/models/image/common/ImageModel.scala:47 — ``ImageClassifier.loadModel``
pulls an analytics-zoo-published weight artifact).  There is no
analytics-zoo weight zoo for this framework, so the equivalent user
journey — "load a pretrained resnet-50 and predict" — is served by
importing the ecosystem's published checkpoints directly:

* **torchvision** ``.pth``/``.pt`` state_dicts (resnet family — the
  block layout here matches torchvision's v1.5, and
  ``resnet(conv_padding="torch")`` reproduces its padding alignment
  exactly);
* **tf.keras / keras-applications** models or ``.h5``/``.keras`` files
  (vgg family — architectures match layer-for-layer).

Both sources are normalised into one canonical group sequence and
installed by a single loop: mapping is positional over the
deterministic builder layer order (the same contract ObjectDetector
persistence uses), and both sides must agree exactly — any shape or
kind mismatch raises with the offending slot named.

Numeric fidelity notes:
* a source conv bias facing a bias-free target conv is folded into the
  IMMEDIATELY FOLLOWING BN's running mean (BN(conv(x)+b) ==
  BN'(conv(x)) with mean' = mean - b); if no BN directly follows, the
  import refuses rather than guessing;
* the source BN epsilon is folded into the stored ``moving_var``
  (``var' = var + eps_src - eps_layer`` so the layer's
  ``rsqrt(var' + eps_layer)`` equals the source's
  ``rsqrt(var + eps_src)`` exactly) — unlike patching the live layer,
  this survives save_model/load_weights round-trips.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import ChainedPreprocessing
from analytics_zoo_tpu.feature.image import (
    ImageCenterCrop, ImageChannelNormalize, ImageChannelOrder, ImageResize)
from analytics_zoo_tpu.models.image.common import ImageConfigure

_TORCH_BN_EPS = 1e-5

# canonical group: ("conv"|"dense", {kernel[, bias], __name__})
#               or ("bn", {gamma, beta, moving_mean, moving_var,
#                          epsilon, __name__})
Group = Tuple[str, Dict[str, Any]]


# ------------------------------------------------------------- model slots
def _model_slots(model) -> List[Tuple[str, Any]]:
    """The model's weight-bearing layers, in builder order, classified
    as 'conv' / 'bn' / 'dense'."""
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        BatchNormalization, Dense)
    from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _ConvND

    slots: List[Tuple[str, Any]] = []
    for layer in model.layers:
        if isinstance(layer, BatchNormalization):
            slots.append(("bn", layer))
        elif isinstance(layer, _ConvND):
            slots.append(("conv", layer))
        elif isinstance(layer, Dense):
            slots.append(("dense", layer))
    return slots


# ----------------------------------------------------- source -> groups
def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _torch_groups(state_dict, bn_eps: float = _TORCH_BN_EPS,
                  skip_prefixes: Tuple[str, ...] = ()) -> List[Group]:
    """Normalise a torch ``state_dict`` (insertion-ordered = module
    definition order) into canonical groups.

    ``bn_eps``: the source model's BatchNorm2d epsilon — NOT stored in
    the state_dict, so families that deviate from torch's 1e-5 default
    (e.g. googlenet's 1e-3) must say so or the fold into moving_var is
    silently off.  ``skip_prefixes`` drops checkpoint modules the
    target intentionally does not build (e.g. googlenet's aux towers,
    which only exist for training-time loss shaping)."""
    grouped: Dict[str, Dict[str, np.ndarray]] = {}
    order: List[str] = []
    for key, tensor in state_dict.items():
        prefix, _, leaf = key.rpartition(".")
        if leaf == "num_batches_tracked":
            continue
        if any(key.startswith(p) for p in skip_prefixes):
            continue
        if prefix not in grouped:
            grouped[prefix] = {}
            order.append(prefix)
        grouped[prefix][leaf] = _to_numpy(tensor)

    out: List[Group] = []
    for prefix in order:
        g = grouped[prefix]
        if "running_mean" in g:
            out.append(("bn", {
                "gamma": g["weight"], "beta": g["bias"],
                "moving_mean": g["running_mean"],
                "moving_var": g["running_var"],
                "epsilon": bn_eps, "__name__": prefix}))
        elif g["weight"].ndim == 4:
            # OIHW -> HWIO; also correct for grouped/depthwise convs
            # (torch (C,1,kh,kw) -> (kh,kw,1,C), I = in/groups)
            ng: Dict[str, Any] = {
                "kernel": np.transpose(g["weight"], (2, 3, 1, 0)),
                "__name__": prefix}
            if "bias" in g:
                ng["bias"] = g["bias"]
            out.append(("conv", ng))
        elif g["weight"].ndim == 2:
            # __cmajor__: torch flattens conv maps C-major (C,H,W);
            # this framework flattens channels-last (H,W,C) — the
            # installer permutes the first dense after a conv
            ng = {"kernel": g["weight"].T, "__name__": prefix,
                  "__cmajor__": True}
            if "bias" in g:
                ng["bias"] = g["bias"]
            out.append(("dense", ng))
        else:
            raise ValueError(
                f"cannot classify checkpoint module {prefix!r} "
                f"(weight shape {g['weight'].shape})")
    return out


def _keras_groups(keras_model) -> List[Group]:
    """Normalise a live tf.keras model into canonical groups."""
    out: List[Group] = []
    for kl in keras_model.layers:
        cls = type(kl).__name__
        w = [np.asarray(a) for a in kl.get_weights()]
        if cls in ("Conv2D", "DepthwiseConv2D"):
            kernel = w[0]
            if cls == "DepthwiseConv2D":
                # keras depthwise (kh, kw, C, mult) -> grouped HWIO
                kh, kw, c, mult = kernel.shape
                kernel = kernel.reshape(kh, kw, 1, c * mult)
            g: Dict[str, Any] = {"kernel": kernel, "__name__": kl.name}
            if len(w) > 1:
                g["bias"] = w[1]
            out.append(("conv", g))
        elif cls == "Dense":
            g = {"kernel": w[0], "__name__": kl.name}
            if len(w) > 1:
                g["bias"] = w[1]
            out.append(("dense", g))
        elif cls == "BatchNormalization":
            gamma, beta, mean, var = w
            out.append(("bn", {
                "gamma": gamma, "beta": beta, "moving_mean": mean,
                "moving_var": var, "epsilon": float(kl.epsilon),
                "__name__": kl.name}))
        elif w:
            raise ValueError(
                f"unsupported source layer {cls} ({kl.name}) with "
                "weights")
    return out


# -------------------------------------------------------------- installer
def _install(model, groups: List[Group]) -> None:
    """Install canonical groups into the model's weight slots."""
    slots = _model_slots(model)
    if len(groups) != len(slots):
        raise ValueError(
            f"checkpoint has {len(groups)} weight modules but the model "
            f"has {len(slots)} weight layers — architectures differ")

    # lazy init (get_variables inits only when the model has none yet)
    variables = model.get_variables()
    params, state = variables["params"], variables["state"]
    flatten_shapes = _flatten_fed_denses(model)

    for i, ((skind, layer), (gkind, g)) in enumerate(zip(slots, groups)):
        name = layer.name
        if skind == "dense" and gkind == "conv" \
                and tuple(np.shape(g["kernel"])[:2]) == (1, 1):
            # keras-applications MobileNet-style classifier: a 1x1
            # conv on the pooled (1,1,C) map IS a Dense over C
            g = dict(g, kernel=np.reshape(
                g["kernel"], np.shape(g["kernel"])[2:]))
            gkind = "dense"
        if skind != gkind:
            raise ValueError(
                f"layer {name} is a {skind} but checkpoint module "
                f"{g['__name__']!r} is a {gkind}")
        if skind in ("conv", "dense"):
            kernel = g["kernel"]
            if skind == "dense" and g.get("__cmajor__") \
                    and name in flatten_shapes:
                # a Dense fed by Flatten(H, W, C): reorder its input
                # features from torch's C-major (C, H, W) flatten —
                # shapes match either way, so skipping this would be
                # SILENTLY wrong (post-GAP heads have no Flatten and
                # need no permute)
                _b, h, w, c = flatten_shapes[name]
                if kernel.shape[0] != h * w * c:
                    raise ValueError(
                        f"{name}: dense input {kernel.shape[0]} != "
                        f"flattened ({h},{w},{c}) feature map")
                kernel = kernel.reshape(c, h, w, -1) \
                    .transpose(1, 2, 0, 3) \
                    .reshape(h * w * c, kernel.shape[1])
            _assign(params, name, "kernel", kernel)
            if "bias" in g:
                if "bias" in params[name]:
                    _assign(params, name, "bias", g["bias"])
                elif skind == "conv" and i + 1 < len(slots) \
                        and slots[i + 1][0] == "bn" \
                        and groups[i + 1][0] == "bn":
                    # fold ONLY into the BN that consumes THIS conv's
                    # output (the immediately following slot) — folding
                    # into a later BN would be silently wrong
                    groups[i + 1][1]["moving_mean"] = \
                        groups[i + 1][1]["moving_mean"] - g["bias"]
                else:
                    raise ValueError(
                        f"checkpoint module {g['__name__']!r} has a "
                        f"bias but target layer {name} is bias-free "
                        "and not directly followed by a BN to fold "
                        "it into")
        else:  # bn
            _assign(params, name, "gamma", g["gamma"])
            _assign(params, name, "beta", g["beta"])
            _assign(state, name, "moving_mean", g["moving_mean"])
            # epsilon folded into the stored variance — exact, and it
            # survives save/load (the layer object keeps its own eps)
            var = g["moving_var"] + (g["epsilon"] - layer.epsilon)
            _assign(state, name, "moving_var", var)
    model.set_variables({"params": params, "state": state})


def _flatten_fed_denses(model) -> Dict[str, Tuple[int, ...]]:
    """Map each Dense fed (directly, through weightless layers) by a
    4-D Flatten to that Flatten's built input shape (b, H, W, C)."""
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        BatchNormalization, Dense, Flatten)
    from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _ConvND

    out: Dict[str, Tuple[int, ...]] = {}
    last_flat = None
    for l in model.layers:
        if isinstance(l, Flatten):
            # the model is built by the time the installer runs, so an
            # unknown shape must RAISE — silently skipping the permute
            # would corrupt the import with no error
            shape = tuple(l.get_input_shape())
            last_flat = shape if len(shape) == 4 else None
        elif isinstance(l, Dense):
            if last_flat is not None:
                out[l.name] = last_flat
            last_flat = None     # only the FIRST dense sees raw H*W*C
        elif isinstance(l, (_ConvND, BatchNormalization)):
            last_flat = None
    return out


def _assign(tree, layer_name: str, key: str, value: np.ndarray) -> None:
    cur = tree[layer_name][key]
    if tuple(np.shape(cur)) != tuple(np.shape(value)):
        raise ValueError(
            f"{layer_name}.{key}: checkpoint shape "
            f"{tuple(np.shape(value))} != model shape "
            f"{tuple(np.shape(cur))}")
    tree[layer_name][key] = np.asarray(value).astype(
        np.asarray(cur).dtype)


# --------------------------------------------------------------- entries
def load_torch_state_dict(model, state_dict,
                          bn_eps: float = _TORCH_BN_EPS,
                          skip_prefixes: Tuple[str, ...] = ()) -> None:
    """Import a torchvision-layout state_dict into ``model`` in place.

    ``state_dict`` may be the dict itself or a checkpoint dict holding
    one under the conventional ``"state_dict"`` key.  ``bn_eps`` /
    ``skip_prefixes``: see ``_torch_groups``.
    """
    inner = state_dict.get("state_dict") \
        if isinstance(state_dict, dict) else None
    if isinstance(inner, dict):
        state_dict = inner
    _install(model, _torch_groups(state_dict, bn_eps=bn_eps,
                                  skip_prefixes=skip_prefixes))


def load_keras_model(model, keras_model) -> None:
    """Import a tf.keras model's weights into ``model`` in place.

    ``keras_model`` is a live tf.keras ``Model`` (e.g.
    ``tf.keras.applications.VGG16(...)`` after ``load_weights``) or a
    path loadable by ``tf.keras.models.load_model``.
    """
    if isinstance(keras_model, (str, os.PathLike)):
        import tensorflow as tf
        keras_model = tf.keras.models.load_model(keras_model,
                                                 compile=False)
    _install(model, _keras_groups(keras_model))


def infer_source(src) -> Optional[str]:
    """'torchvision' | 'keras' from the checkpoint's type / extension."""
    if isinstance(src, (str, os.PathLike)):
        ext = os.path.splitext(str(src))[1].lower()
        return {".pth": "torchvision", ".pt": "torchvision",
                ".h5": "keras", ".keras": "keras"}.get(ext)
    if isinstance(src, dict):
        return "torchvision"
    if type(src).__module__.split(".")[0] in ("keras", "tensorflow",
                                              "tf_keras"):
        return "keras"
    return None


def load_pretrained(model, src, source: Optional[str] = None,
                    **torch_kw) -> None:
    """Dispatch on ``source`` ('torchvision' | 'keras') or the file
    extension (.pth/.pt vs .h5/.keras).  ``torch_kw`` forwards
    family-specific import options (``bn_eps``, ``skip_prefixes``) to
    ``load_torch_state_dict``."""
    source = source or infer_source(src)
    if source == "torchvision":
        if isinstance(src, (str, os.PathLike)):
            import torch
            src = torch.load(src, map_location="cpu", weights_only=True)
        load_torch_state_dict(model, src, **torch_kw)
    elif source == "keras":
        if torch_kw:
            raise ValueError("bn_eps/skip_prefixes only apply to "
                             "torchvision checkpoints")
        load_keras_model(model, src)
    else:
        raise ValueError(
            f"cannot infer checkpoint source for {src!r}; pass "
            "source='torchvision' or source='keras'")


# Per-model preprocess for pretrained weights — the per-name configure
# table of ImageClassificationConfig.scala:190 (means/std in the 0-255
# pixel domain the ImageSet pipeline produces).
_TV_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
_TV_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)
_CAFFE_MEAN_BGR = (103.939, 116.779, 123.68)


def pretrained_configure(
        model_name: str, source: str = "torchvision",
        input_shape: Tuple[int, int, int] = (224, 224, 3)
) -> ImageConfigure:
    """ImageConfigure matching the preprocessing the published weights
    were trained with, cropped to the MODEL'S input size (published
    recipes use 256-resize/224-crop; other input sizes scale the
    resize by the same 256/224 shortest-side ratio)."""
    crop_h, crop_w = int(input_shape[0]), int(input_shape[1])
    resize_h = round(crop_h * 256 / 224)
    resize_w = round(crop_w * 256 / 224)
    steps = [ImageResize(resize_h, resize_w),
             ImageCenterCrop(crop_h, crop_w)]
    if source == "torchvision":
        if model_name == "inception-v1":
            # torchvision googlenet weights were ported from TF-slim;
            # its transform_input undoes the standard normalize and
            # applies (x/255 - 0.5)/0.5 — fold that straight in
            steps.append(ImageChannelNormalize(127.5, 127.5, 127.5,
                                               127.5, 127.5, 127.5))
        else:
            steps.append(ImageChannelNormalize(*_TV_MEAN, *_TV_STD))
    elif source == "keras":
        if model_name.startswith("mobilenet"):
            # keras "tf" mode: RGB, x/127.5 - 1
            steps.append(ImageChannelNormalize(127.5, 127.5, 127.5,
                                               127.5, 127.5, 127.5))
        else:
            # caffe-style: BGR order, mean subtraction (VGG lineage)
            steps.append(ImageChannelOrder())   # RGB -> BGR
            steps.append(ImageChannelNormalize(*_CAFFE_MEAN_BGR))
    else:
        raise ValueError(f"unknown pretrained source {source!r}")
    return ImageConfigure(preprocessor=ChainedPreprocessing(steps),
                          batch_per_partition=4)
