"""Image classification nets: LeNet, ResNet-18/34/50, Inception-v1.

Reference: the ImageClassification model family
(zoo/models/image/imageclassification/ImageClassificationConfig.scala:190
loads published analytics-zoo models by name) and the two ImageNet
training recipes (examples/inception/Train.scala:31,
examples/resnet/TrainImageNet.scala).

TPU design notes: NHWC layout throughout, BN+ReLU after each conv (XLA
fuses both into the conv epilogue), residual adds via Merge("sum"),
global-average-pool head.  bf16 conv compute with f32 accumulation comes
from the layer implementations.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from analytics_zoo_tpu.models.image.common import ImageConfigure, ImageModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Convolution2D, Dense,
    Dropout, Flatten, GlobalAveragePooling2D, MaxPooling2D, Merge,
    SpaceToDepth2D, ZeroPadding2D,
)


def _conv_bn(x, filters, k, stride=1, act=True, border="same",
             torch_pad=False):
    """Conv→BN→activation.  ``act``: True = relu, a string = that
    activation, False = none.  ``torch_pad`` reproduces the torch/Caffe
    lineage's explicit SYMMETRIC padding (pad (k-1)//2 on both sides,
    then a valid conv): XLA's SAME pads asymmetrically under stride 2
    (e.g. 0/1 for k=3), which samples different pixel positions —
    imported torchvision checkpoints are only numerically faithful
    with the source's alignment.  For stride 1 the two are identical,
    so SAME is kept (one op instead of two)."""
    if torch_pad and stride > 1 and k > 1:
        p = (k - 1) // 2
        x = ZeroPadding2D((p, p))(x)
        border = "valid"
    x = Convolution2D(filters, k, k, subsample=(stride, stride),
                      border_mode=border, bias=False)(x)
    x = BatchNormalization()(x)
    if act:
        x = Activation("relu" if act is True else act)(x)
    return x


def _check_conv_padding(conv_padding: str) -> bool:
    """Validate the conv_padding option; returns the torch_pad flag."""
    if conv_padding not in ("same", "torch"):
        raise ValueError(f"conv_padding must be 'same' or 'torch', "
                         f"got {conv_padding!r}")
    return conv_padding == "torch"


def _check_variant(variant: str) -> bool:
    """Validate a 'zoo' | 'torchvision' variant option; returns True
    for the torchvision graph variant."""
    if variant not in ("zoo", "torchvision"):
        raise ValueError(f"variant must be 'zoo' or 'torchvision', "
                         f"got {variant!r}")
    return variant == "torchvision"


def _stem_pool(x, torch_pad: bool):
    """The 3x3/stride-2 stem maxpool shared by the conv7 families:
    torch alignment = zero-pad(1,1) + valid pool (post-ReLU inputs are
    >= 0, so zero padding never wins the max)."""
    if torch_pad:
        x = ZeroPadding2D((1, 1))(x)
        return MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                            border_mode="valid")(x)
    return MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                        border_mode="same")(x)


# ------------------------------------------------------------------ LeNet
def lenet(num_classes: int = 10,
          input_shape: Tuple[int, int, int] = (28, 28, 1)) -> Model:
    inp = Input(shape=input_shape)
    x = Convolution2D(6, 5, 5, border_mode="same",
                      activation="tanh")(inp)
    x = MaxPooling2D()(x)
    x = Convolution2D(12, 5, 5, activation="tanh")(x)
    x = MaxPooling2D()(x)
    x = Flatten()(x)
    x = Dense(100, activation="tanh")(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


# ----------------------------------------------------------------- ResNet
def _basic_block(x, filters, stride, torch_pad=False):
    shortcut = x
    y = _conv_bn(x, filters, 3, stride, torch_pad=torch_pad)
    y = _conv_bn(y, filters, 3, 1, act=False)
    if stride != 1 or x.shape[-1] != filters:
        shortcut = _conv_bn(x, filters, 1, stride, act=False)
    out = Merge(mode="sum")([y, shortcut])
    return Activation("relu")(out)


def _bottleneck_block(x, filters, stride, torch_pad=False):
    shortcut = x
    y = _conv_bn(x, filters, 1, 1)
    y = _conv_bn(y, filters, 3, stride, torch_pad=torch_pad)
    y = _conv_bn(y, 4 * filters, 1, 1, act=False)
    if stride != 1 or x.shape[-1] != 4 * filters:
        shortcut = _conv_bn(x, 4 * filters, 1, stride, act=False)
    out = Merge(mode="sum")([y, shortcut])
    return Activation("relu")(out)


_RESNET_SPECS = {
    18: (_basic_block, (2, 2, 2, 2)),
    34: (_basic_block, (3, 4, 6, 3)),
    50: (_bottleneck_block, (3, 4, 6, 3)),
    101: (_bottleneck_block, (3, 4, 23, 3)),
    152: (_bottleneck_block, (3, 8, 36, 3)),
}


def resnet(depth: int = 50, num_classes: int = 1000,
           input_shape: Tuple[int, int, int] = (224, 224, 3),
           stem: str = "conv7", conv_padding: str = "same") -> Model:
    """ResNet for ImageNet-scale inputs (TrainImageNet.scala recipe).

    ``stem="conv7"`` is the classic 7x7/stride-2 stem; ``"space_to_depth"``
    is the MXU-efficient equivalent (2x2 pixel blocks packed into 12
    channels, then a 4x4/stride-1 conv whose 8x8-pixel receptive field
    covers the 7x7 original) — same output shape and capacity, ~4x the
    stem's MXU utilisation on TPU.

    ``conv_padding="torch"`` uses the torch/Caffe lineage's explicit
    symmetric padding on the stem, the stem maxpool, and every
    stride-2 3x3 conv (see ``_conv_bn``) — the alignment published
    torchvision checkpoints were trained with (the block layout here
    already matches torchvision's v1.5: stride on the 3x3).  The
    default SAME padding is what you want when training from scratch
    (fewer ops, identical capacity).
    """
    block, reps = _RESNET_SPECS[depth]
    torch_pad = _check_conv_padding(conv_padding)
    inp = Input(shape=input_shape)
    if stem == "space_to_depth":
        x = SpaceToDepth2D(2)(inp)
        x = _conv_bn(x, 64, 4, 1)
    elif stem == "conv7":
        x = _conv_bn(inp, 64, 7, 2, torch_pad=torch_pad)
    else:
        raise ValueError(f"unknown stem {stem!r}; "
                         "expected 'conv7' or 'space_to_depth'")
    x = _stem_pool(x, torch_pad)
    filters = 64
    for stage, n in enumerate(reps):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            x = block(x, filters, stride, torch_pad=torch_pad)
        filters *= 2
    x = GlobalAveragePooling2D()(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


# ------------------------------------------------------------ Inception-v1
def _inception_module(x, f1, f3r, f3, f5r, f5, proj, b5_k=5):
    b1 = _conv_bn(x, f1, 1)
    b3 = _conv_bn(_conv_bn(x, f3r, 1), f3, 3)
    b5 = _conv_bn(_conv_bn(x, f5r, 1), f5, b5_k)
    bp = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                      border_mode="same")(x)
    bp = _conv_bn(bp, proj, 1)
    return Merge(mode="concat", concat_axis=-1)([b1, b3, b5, bp])


def inception_v1(num_classes: int = 1000,
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 variant: str = "zoo") -> Model:
    """GoogLeNet / Inception-v1 (examples/inception/Train.scala:31
    workload).

    ``variant="torchvision"`` reproduces torchvision's ``googlenet``
    graph exactly so published checkpoints import faithfully: the
    explicit pad-3 stem alignment, and a 3x3 kernel on the "5x5"
    branch (torchvision inherited that substitution from the TF-slim
    checkpoint it ported; the published weights have 3x3 shapes).
    The stride-2 maxpools stay ``same`` — on this net's even extents
    SAME's right-only padding selects the same windows as
    torchvision's ceil_mode, and zero padding never wins a max over
    post-ReLU inputs.  The aux towers are inference-irrelevant and
    not built; the importer skips their checkpoint modules."""
    tv = _check_variant(variant)
    if tv and (input_shape[0] % 32 or input_shape[1] % 32):
        # the SAME-pool == ceil_mode-pool equivalence (docstring) holds
        # only while every stride-2 stage sees an even extent; 5
        # halvings -> multiples of 32 keep the whole stack even
        raise ValueError(
            "variant='torchvision' needs input height/width divisible "
            f"by 32 for checkpoint-faithful pooling; got "
            f"{tuple(input_shape[:2])}")
    b5_k = 3 if tv else 5
    inp = Input(shape=input_shape)
    x = _conv_bn(inp, 64, 7, 2, torch_pad=tv)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _conv_bn(x, 64, 1)
    x = _conv_bn(x, 192, 3)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_module(x, 64, 96, 128, 16, 32, 32,
                          b5_k=b5_k)                      # 3a
    x = _inception_module(x, 128, 128, 192, 32, 96, 64,
                          b5_k=b5_k)                      # 3b
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_module(x, 192, 96, 208, 16, 48, 64,
                          b5_k=b5_k)                      # 4a
    x = _inception_module(x, 160, 112, 224, 24, 64, 64,
                          b5_k=b5_k)                      # 4b
    x = _inception_module(x, 128, 128, 256, 24, 64, 64,
                          b5_k=b5_k)                      # 4c
    x = _inception_module(x, 112, 144, 288, 32, 64, 64,
                          b5_k=b5_k)                      # 4d
    x = _inception_module(x, 256, 160, 320, 32, 128, 128,
                          b5_k=b5_k)                      # 4e
    # torchvision's maxpool4 is kernel-2/stride-2 (not 3x3)
    pool4 = (2, 2) if tv else (3, 3)
    x = MaxPooling2D(pool_size=pool4, strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_module(x, 256, 160, 320, 32, 128, 128,
                          b5_k=b5_k)                      # 5a
    x = _inception_module(x, 384, 192, 384, 48, 128, 128,
                          b5_k=b5_k)                      # 5b
    x = GlobalAveragePooling2D()(x)
    x = Dropout(0.2 if tv else 0.4)(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


def mobilenet(num_classes: int = 1000,
              input_shape: Tuple[int, int, int] = (224, 224, 3),
              alpha: float = 1.0, activation: str = "relu") -> Model:
    """MobileNet-v1 (the published "mobilenet" family of
    ImageClassificationConfig.scala): each block is depthwise 3x3 →
    BN → act → pointwise 1x1 → BN → act — BOTH nonlinearities, per
    the paper (a fused separable conv would be a low-rank factorized
    conv, not MobileNet).  ``activation="relu6"`` matches the
    published keras-applications weights (XLA SAME padding already
    matches keras's zero-pad(0,1)+valid alignment on stride 2)."""
    def dw_block(x, in_ch, out_ch, stride):
        # depthwise: one 3x3 filter per input channel (groups=in_ch)
        x = Convolution2D(in_ch, 3, 3, subsample=(stride, stride),
                          border_mode="same", bias=False,
                          groups=in_ch)(x)
        x = BatchNormalization()(x)
        x = Activation(activation)(x)
        x = Convolution2D(out_ch, 1, 1, bias=False)(x)
        x = BatchNormalization()(x)
        return Activation(activation)(x)

    inp = Input(shape=input_shape)
    ch = int(32 * alpha)
    x = _conv_bn(inp, ch, 3, 2, act=activation)
    for filters, stride in ((64, 1), (128, 2), (128, 1), (256, 2),
                            (256, 1), (512, 2), (512, 1), (512, 1),
                            (512, 1), (512, 1), (512, 1), (1024, 2),
                            (1024, 1)):
        out_ch = int(filters * alpha)
        x = dw_block(x, ch, out_ch, stride)
        ch = out_ch
    x = GlobalAveragePooling2D()(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


def vgg(depth: int = 16, num_classes: int = 1000,
        input_shape: Tuple[int, int, int] = (224, 224, 3)) -> Model:
    """VGG-16/19 (published "vgg-16"/"vgg-19")."""
    cfg = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}[depth]
    inp = Input(shape=input_shape)
    x = inp
    filters = 64
    for n_convs in cfg:
        for _ in range(n_convs):
            x = Convolution2D(filters, 3, 3, border_mode="same",
                              activation="relu")(x)
        x = MaxPooling2D(pool_size=(2, 2))(x)
        filters = min(filters * 2, 512)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


def squeezenet(num_classes: int = 1000,
               input_shape: Tuple[int, int, int] = (224, 224, 3)
               ) -> Model:
    """SqueezeNet v1.1 (published "squeezenet")."""
    def fire(x, squeeze, expand):
        s = Convolution2D(squeeze, 1, 1, activation="relu")(x)
        e1 = Convolution2D(expand, 1, 1, activation="relu")(s)
        e3 = Convolution2D(expand, 3, 3, border_mode="same",
                           activation="relu")(s)
        return Merge(mode="concat")([e1, e3])

    inp = Input(shape=input_shape)
    x = Convolution2D(64, 3, 3, subsample=(2, 2),
                      activation="relu")(inp)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = fire(x, 16, 64)
    x = fire(x, 16, 64)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = fire(x, 32, 128)
    x = fire(x, 32, 128)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = fire(x, 48, 192)
    x = fire(x, 48, 192)
    x = fire(x, 64, 256)
    x = fire(x, 64, 256)
    x = Dropout(0.5)(x)
    # the paper (and torchvision) applies ReLU to conv10 before the
    # global pool — outputs are non-negative class activations
    x = Convolution2D(num_classes, 1, 1, activation="relu")(x)
    out = GlobalAveragePooling2D()(x)
    return Model(inp, out)


def densenet(depth: int = 121, num_classes: int = 1000,
             input_shape: Tuple[int, int, int] = (224, 224, 3),
             growth_rate: int = None, blocks: Sequence[int] = None,
             conv_padding: str = "same") -> Model:
    """DenseNet-121/161/169 (incl. the published "densenet-161"; block
    configs and growth rates per the DenseNet paper).  ``blocks``
    overrides the per-stage layer counts (custom/test-scale configs).

    ``conv_padding="torch"``: explicit symmetric padding on the stem
    conv + maxpool (the only stride-2 ops with a kernel > 1), matching
    torchvision checkpoints — every other conv is 1x1 or stride-1
    3x3/SAME, which already agree."""
    try:
        default_blocks, default_growth = {
            121: ((6, 12, 24, 16), 32),
            161: ((6, 12, 36, 24), 48),
            169: ((6, 12, 32, 32), 32),
        }[depth]
    except KeyError:
        raise ValueError(f"densenet depth must be 121/161/169, "
                         f"got {depth}") from None
    blocks = tuple(blocks) if blocks is not None else default_blocks
    growth_rate = growth_rate or default_growth

    def dense_block(x, n_layers):
        for _ in range(n_layers):
            y = BatchNormalization()(x)
            y = Activation("relu")(y)
            y = Convolution2D(4 * growth_rate, 1, 1, bias=False)(y)
            y = BatchNormalization()(y)
            y = Activation("relu")(y)
            y = Convolution2D(growth_rate, 3, 3, border_mode="same",
                              bias=False)(y)
            x = Merge(mode="concat")([x, y])
        return x

    def transition(x, out_ch):
        x = BatchNormalization()(x)
        x = Activation("relu")(x)
        x = Convolution2D(out_ch, 1, 1, bias=False)(x)
        return AveragePooling2D(pool_size=(2, 2))(x)

    torch_pad = _check_conv_padding(conv_padding)
    inp = Input(shape=input_shape)
    x = _conv_bn(inp, 2 * growth_rate, 7, 2, torch_pad=torch_pad)
    x = _stem_pool(x, torch_pad)
    ch = 2 * growth_rate
    for i, n_layers in enumerate(blocks):
        x = dense_block(x, n_layers)
        ch += n_layers * growth_rate
        if i < len(blocks) - 1:
            ch //= 2
            x = transition(x, ch)
    x = BatchNormalization()(x)
    x = Activation("relu")(x)
    x = GlobalAveragePooling2D()(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


def alexnet(num_classes: int = 1000,
            input_shape: Tuple[int, int, int] = (227, 227, 3),
            variant: str = "zoo") -> Model:
    """AlexNet (published "alexnet"; LRN replaced by BN, the modern
    equivalent).

    ``variant="torchvision"`` builds torchvision's exact graph instead
    (224 input, pad-2 stem, no norm layers, dropout-first classifier)
    so published ``alexnet .pth`` checkpoints import faithfully."""
    if _check_variant(variant):
        if input_shape == (227, 227, 3):
            input_shape = (224, 224, 3)    # torchvision's input size
        inp = Input(shape=input_shape)
        x = ZeroPadding2D((2, 2))(inp)
        x = Convolution2D(64, 11, 11, subsample=(4, 4),
                          activation="relu")(x)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
        x = Convolution2D(192, 5, 5, border_mode="same",
                          activation="relu")(x)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
        x = Convolution2D(384, 3, 3, border_mode="same",
                          activation="relu")(x)
        x = Convolution2D(256, 3, 3, border_mode="same",
                          activation="relu")(x)
        x = Convolution2D(256, 3, 3, border_mode="same",
                          activation="relu")(x)
        x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
        x = Flatten()(x)
        x = Dropout(0.5)(x)
        x = Dense(4096, activation="relu")(x)
        x = Dropout(0.5)(x)
        x = Dense(4096, activation="relu")(x)
        out = Dense(num_classes)(x)
        return Model(inp, out)
    inp = Input(shape=input_shape)
    x = Convolution2D(96, 11, 11, subsample=(4, 4),
                      activation="relu")(inp)
    x = BatchNormalization()(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = Convolution2D(256, 5, 5, border_mode="same",
                      activation="relu")(x)
    x = BatchNormalization()(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = Convolution2D(384, 3, 3, border_mode="same",
                      activation="relu")(x)
    x = Convolution2D(384, 3, 3, border_mode="same",
                      activation="relu")(x)
    x = Convolution2D(256, 3, 3, border_mode="same",
                      activation="relu")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    out = Dense(num_classes)(x)
    return Model(inp, out)


_BUILDERS = {
    "lenet": lenet,
    "resnet-18": lambda **kw: resnet(18, **kw),
    "resnet-34": lambda **kw: resnet(34, **kw),
    "resnet-50": lambda **kw: resnet(50, **kw),
    "resnet-101": lambda **kw: resnet(101, **kw),
    "inception-v1": inception_v1,
    "mobilenet": mobilenet,
    "vgg-16": lambda **kw: vgg(16, **kw),
    "vgg-19": lambda **kw: vgg(19, **kw),
    "squeezenet": squeezenet,
    "densenet-121": lambda **kw: densenet(121, **kw),
    "densenet-161": lambda **kw: densenet(161, **kw),
    "densenet-169": lambda **kw: densenet(169, **kw),
    "alexnet": alexnet,
}


class ImageClassifier(ImageModel):
    """Build a named classification net (the by-name loading surface of
    ImageClassificationConfig.scala).

    ``pretrained`` imports a published checkpoint — a torchvision
    ``.pth`` state_dict or a tf.keras model / ``.h5`` file (see
    ``pretrained.py``) — and installs the matching per-model preprocess
    configure, the reference's load-by-name +
    ImageClassificationConfig behavior."""

    def __init__(self, model_name: str = "resnet-50",
                 num_classes: int = 1000,
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 config: ImageConfigure = None,
                 pretrained=None, source: str = None):
        if model_name not in _BUILDERS:
            raise ValueError(
                f"unknown model {model_name!r}; "
                f"available: {sorted(_BUILDERS)}")
        self._builder = _BUILDERS[model_name]
        self._kw = dict(num_classes=num_classes, input_shape=input_shape)
        if pretrained is not None:
            from analytics_zoo_tpu.models.image.imageclassification \
                .pretrained import infer_source
            # source must be known BEFORE build: torchvision resnets
            # need the torch padding alignment in the graph
            source = source or infer_source(pretrained)
            if source == "torchvision" and model_name.startswith(
                    ("resnet", "densenet")):
                self._kw["conv_padding"] = "torch"
            if source == "torchvision" and model_name in (
                    "alexnet", "inception-v1"):
                self._kw["variant"] = "torchvision"
            if source == "keras" and model_name == "mobilenet":
                # keras-applications MobileNet weights were trained
                # with relu6
                self._kw["activation"] = "relu6"
        super().__init__(config)
        if pretrained is not None:
            from analytics_zoo_tpu.models.image.imageclassification \
                .pretrained import load_pretrained, pretrained_configure
            torch_kw = {}
            if source == "torchvision" and model_name == "inception-v1":
                # torchvision googlenet: BN eps 1e-3 (not torch's 1e-5
                # default) and training-only aux towers in the ckpt
                torch_kw = dict(bn_eps=1e-3,
                                skip_prefixes=("aux1.", "aux2."))
            load_pretrained(self.model, pretrained, source=source,
                            **torch_kw)
            if config is None:
                self.config = pretrained_configure(
                    model_name, source, input_shape=input_shape)

    def build_model(self):
        return self._builder(**self._kw)
