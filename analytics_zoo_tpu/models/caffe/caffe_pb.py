"""Caffe protobuf schema (subset) over the in-repo wire codec.

Field numbers follow the public BVLC ``caffe.proto``.  Covers the
messages needed to read ``.prototxt`` net definitions (text format) and
``.caffemodel`` weight blobs (binary): NetParameter with both V2
``layer`` and legacy V1 ``layers`` lists, per-layer param messages, and
BlobProto weights.  The reference's loader is
zoo models/caffe/CaffeLoader.scala:718 (+ Converter.scala,
V1LayerConverter.scala); this is its TPU-build equivalent schema.
"""

from __future__ import annotations

from analytics_zoo_tpu.utils.pbwire import Field, Message


class BlobShape(Message):
    FIELDS = [Field(1, "dim", "int64", repeated=True)]


class BlobProto(Message):
    FIELDS = [
        Field(1, "num", "int64"),
        Field(2, "channels", "int64"),
        Field(3, "height", "int64"),
        Field(4, "width", "int64"),
        Field(5, "data", "float", repeated=True),
        Field(6, "diff", "float", repeated=True),
        Field(7, "shape", "msg", msg_cls=BlobShape),
    ]

    def ndarray(self):
        import numpy as np
        arr = np.asarray(self.data, dtype=np.float32)
        if self.shape is not None and self.shape.dim:
            return arr.reshape([int(d) for d in self.shape.dim])
        legacy = [int(self.num), int(self.channels), int(self.height),
                  int(self.width)]
        if any(legacy):
            dims = [d if d else 1 for d in legacy]
            return arr.reshape(dims)
        return arr


class FillerParameter(Message):
    FIELDS = [
        Field(1, "type", "string"),
        Field(2, "value", "float"),
        Field(5, "mean", "float"),
        Field(6, "std", "float"),
    ]

    def __init__(self, **kw):
        kw.setdefault("type", "constant")
        kw.setdefault("std", 1.0)
        super().__init__(**kw)


class ConvolutionParameter(Message):
    FIELDS = [
        Field(1, "num_output", "uint64"),
        Field(2, "bias_term", "bool"),
        Field(3, "pad", "uint64", repeated=True),
        Field(4, "kernel_size", "uint64", repeated=True),
        Field(5, "group", "uint64"),
        Field(6, "stride", "uint64", repeated=True),
        Field(7, "weight_filler", "msg", msg_cls=FillerParameter),
        Field(8, "bias_filler", "msg", msg_cls=FillerParameter),
        Field(9, "pad_h", "uint64"),
        Field(10, "pad_w", "uint64"),
        Field(11, "kernel_h", "uint64"),
        Field(12, "kernel_w", "uint64"),
        Field(13, "stride_h", "uint64"),
        Field(14, "stride_w", "uint64"),
        Field(18, "dilation", "uint64", repeated=True),
    ]

    def __init__(self, **kw):
        kw.setdefault("bias_term", True)
        super().__init__(**kw)


class PoolingParameter(Message):
    MAX = 0
    AVE = 1
    STOCHASTIC = 2
    FIELDS = [
        Field(1, "pool", "enum"),
        Field(2, "kernel_size", "uint64"),
        Field(3, "stride", "uint64"),
        Field(4, "pad", "uint64"),
        Field(5, "kernel_h", "uint64"),
        Field(6, "kernel_w", "uint64"),
        Field(7, "stride_h", "uint64"),
        Field(8, "stride_w", "uint64"),
        Field(9, "pad_h", "uint64"),
        Field(10, "pad_w", "uint64"),
        Field(12, "global_pooling", "bool"),
    ]

    def __init__(self, **kw):
        kw.setdefault("stride", 1)
        super().__init__(**kw)


class InnerProductParameter(Message):
    FIELDS = [
        Field(1, "num_output", "uint64"),
        Field(2, "bias_term", "bool"),
        Field(3, "weight_filler", "msg", msg_cls=FillerParameter),
        Field(4, "bias_filler", "msg", msg_cls=FillerParameter),
        Field(5, "axis", "int64"),
        Field(6, "transpose", "bool"),
    ]

    def __init__(self, **kw):
        kw.setdefault("bias_term", True)
        kw.setdefault("axis", 1)
        super().__init__(**kw)


class LRNParameter(Message):
    FIELDS = [
        Field(1, "local_size", "uint64"),
        Field(2, "alpha", "float"),
        Field(3, "beta", "float"),
        Field(4, "norm_region", "enum"),
        Field(5, "k", "float"),
    ]

    def __init__(self, **kw):
        kw.setdefault("local_size", 5)
        kw.setdefault("alpha", 1.0)
        kw.setdefault("beta", 0.75)
        kw.setdefault("k", 1.0)
        super().__init__(**kw)


class BatchNormParameter(Message):
    FIELDS = [
        Field(1, "use_global_stats", "bool"),
        Field(2, "moving_average_fraction", "float"),
        Field(3, "eps", "float"),
    ]

    def __init__(self, **kw):
        kw.setdefault("eps", 1e-5)
        super().__init__(**kw)


class ScaleParameter(Message):
    FIELDS = [
        Field(1, "axis", "int64"),
        Field(2, "num_axes", "int64"),
        Field(3, "filler", "msg", msg_cls=FillerParameter),
        Field(4, "bias_term", "bool"),
        Field(5, "bias_filler", "msg", msg_cls=FillerParameter),
    ]

    def __init__(self, **kw):
        kw.setdefault("axis", 1)
        super().__init__(**kw)


class DropoutParameter(Message):
    FIELDS = [Field(1, "dropout_ratio", "float")]

    def __init__(self, **kw):
        kw.setdefault("dropout_ratio", 0.5)
        super().__init__(**kw)


class ConcatParameter(Message):
    FIELDS = [
        Field(1, "concat_dim", "uint64"),
        Field(2, "axis", "int64"),
    ]

    def __init__(self, **kw):
        kw.setdefault("axis", 1)
        kw.setdefault("concat_dim", 1)
        super().__init__(**kw)


class EltwiseParameter(Message):
    PROD = 0
    SUM = 1
    MAX = 2
    FIELDS = [
        Field(1, "operation", "enum"),
        Field(2, "coeff", "float", repeated=True),
    ]

    def __init__(self, **kw):
        kw.setdefault("operation", 1)
        super().__init__(**kw)


class PowerParameter(Message):
    FIELDS = [
        Field(1, "power", "float"),
        Field(2, "scale", "float"),
        Field(3, "shift", "float"),
    ]

    def __init__(self, **kw):
        kw.setdefault("power", 1.0)
        kw.setdefault("scale", 1.0)
        super().__init__(**kw)


class ReLUParameter(Message):
    FIELDS = [Field(1, "negative_slope", "float")]


class ELUParameter(Message):
    FIELDS = [Field(1, "alpha", "float")]

    def __init__(self, **kw):
        kw.setdefault("alpha", 1.0)
        super().__init__(**kw)


class PReLUParameter(Message):
    FIELDS = [
        Field(1, "filler", "msg", msg_cls=FillerParameter),
        Field(2, "channel_shared", "bool"),
    ]


class SoftmaxParameter(Message):
    FIELDS = [
        Field(1, "engine", "enum"),
        Field(2, "axis", "int64"),
    ]

    def __init__(self, **kw):
        kw.setdefault("axis", 1)
        super().__init__(**kw)


class FlattenParameter(Message):
    FIELDS = [
        Field(1, "axis", "int64"),
        Field(2, "end_axis", "int64"),
    ]

    def __init__(self, **kw):
        kw.setdefault("axis", 1)
        kw.setdefault("end_axis", -1)
        super().__init__(**kw)


class ReshapeParameter(Message):
    FIELDS = [
        Field(1, "shape", "msg", msg_cls=BlobShape),
        Field(2, "axis", "int64"),
        Field(3, "num_axes", "int64"),
    ]

    def __init__(self, **kw):
        kw.setdefault("num_axes", -1)
        super().__init__(**kw)


class SliceParameter(Message):
    FIELDS = [
        Field(1, "slice_dim", "uint64"),
        Field(2, "slice_point", "uint64", repeated=True),
        Field(3, "axis", "int64"),
    ]

    def __init__(self, **kw):
        kw.setdefault("axis", 1)
        super().__init__(**kw)


class InputParameter(Message):
    FIELDS = [Field(1, "shape", "msg", repeated=True, msg_cls=BlobShape)]


class LayerParameter(Message):
    """Caffe V2 layer."""

    FIELDS = [
        Field(1, "name", "string"),
        Field(2, "type", "string"),
        Field(3, "bottom", "string", repeated=True),
        Field(4, "top", "string", repeated=True),
        Field(7, "blobs", "msg", repeated=True, msg_cls=BlobProto),
        Field(10, "phase", "enum"),
        Field(104, "concat_param", "msg", msg_cls=ConcatParameter),
        Field(106, "convolution_param", "msg", msg_cls=ConvolutionParameter),
        Field(108, "dropout_param", "msg", msg_cls=DropoutParameter),
        Field(110, "eltwise_param", "msg", msg_cls=EltwiseParameter),
        Field(117, "inner_product_param", "msg",
              msg_cls=InnerProductParameter),
        Field(118, "lrn_param", "msg", msg_cls=LRNParameter),
        Field(121, "pooling_param", "msg", msg_cls=PoolingParameter),
        Field(122, "power_param", "msg", msg_cls=PowerParameter),
        Field(123, "relu_param", "msg", msg_cls=ReLUParameter),
        Field(125, "softmax_param", "msg", msg_cls=SoftmaxParameter),
        Field(126, "slice_param", "msg", msg_cls=SliceParameter),
        Field(131, "prelu_param", "msg", msg_cls=PReLUParameter),
        Field(133, "reshape_param", "msg", msg_cls=ReshapeParameter),
        Field(135, "flatten_param", "msg", msg_cls=FlattenParameter),
        Field(139, "batch_norm_param", "msg", msg_cls=BatchNormParameter),
        Field(140, "elu_param", "msg", msg_cls=ELUParameter),
        Field(142, "scale_param", "msg", msg_cls=ScaleParameter),
        Field(143, "input_param", "msg", msg_cls=InputParameter),
    ]


# V1LayerParameter.LayerType enum values (caffe.proto)
V1_TYPES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    8: "Flatten", 14: "InnerProduct", 15: "LRN", 17: "Pooling",
    18: "ReLU", 19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss",
    22: "Split", 23: "TanH", 25: "Eltwise", 26: "Power", 33: "Slice",
    35: "AbsVal", 39: "Deconvolution",
}


class V1LayerParameter(Message):
    """Legacy caffe layer (enum-typed)."""

    FIELDS = [
        Field(2, "bottom", "string", repeated=True),
        Field(3, "top", "string", repeated=True),
        Field(4, "name", "string"),
        Field(5, "type", "enum"),
        Field(6, "blobs", "msg", repeated=True, msg_cls=BlobProto),
        Field(9, "concat_param", "msg", msg_cls=ConcatParameter),
        Field(10, "convolution_param", "msg", msg_cls=ConvolutionParameter),
        Field(12, "dropout_param", "msg", msg_cls=DropoutParameter),
        Field(17, "inner_product_param", "msg",
              msg_cls=InnerProductParameter),
        Field(18, "lrn_param", "msg", msg_cls=LRNParameter),
        Field(19, "pooling_param", "msg", msg_cls=PoolingParameter),
        Field(21, "power_param", "msg", msg_cls=PowerParameter),
        Field(30, "relu_param", "msg", msg_cls=ReLUParameter),
        Field(31, "slice_param", "msg", msg_cls=SliceParameter),
        Field(39, "softmax_param", "msg", msg_cls=SoftmaxParameter),
    ]

    def type_name(self) -> str:
        return V1_TYPES.get(int(self.type), f"V1_{self.type}")


class NetParameter(Message):
    FIELDS = [
        Field(1, "name", "string"),
        Field(2, "layers", "msg", repeated=True, msg_cls=V1LayerParameter),
        Field(3, "input", "string", repeated=True),
        Field(4, "input_dim", "int64", repeated=True),
        Field(8, "input_shape", "msg", repeated=True, msg_cls=BlobShape),
        Field(100, "layer", "msg", repeated=True, msg_cls=LayerParameter),
    ]
