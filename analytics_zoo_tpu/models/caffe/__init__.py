"""Caffe import (ref: zoo models/caffe/CaffeLoader.scala)."""

from analytics_zoo_tpu.models.caffe.loader import (  # noqa: F401
    CaffeLoader, load_caffe)
