"""Protobuf text-format parser (the ``.prototxt`` side of the Caffe
loader, ref CaffeLoader.scala:718 which reads the net definition with
``TextFormat.merge``).

Schema-driven against the same Message classes the binary codec uses:
``parse(text, NetParameter)`` returns a populated message.  Supports
the subset the format actually uses in net definitions: ``name: value``
scalars, ``name { ... }`` sub-messages, repeated fields, quoted
strings, bools, enum identifiers, and ``#`` comments.
"""

from __future__ import annotations

import re
from typing import Any, List, Tuple

from analytics_zoo_tpu.utils.pbwire import Field, Message

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<comment>\#[^\n]*)
    | (?P<brace>[{}])
    | (?P<colon>:)
    | (?P<string>"(?:[^"\\]|\\.)*")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<number>[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?)
    )""", re.VERBOSE)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"prototxt parse error at: {text[pos:pos+40]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment" or kind is None:
            continue
        tokens.append((kind, m.group(kind)))
    return tokens


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        tok = self.peek()
        if tok is None:
            raise ValueError("unexpected end of prototxt")
        self.pos += 1
        return tok

    def parse_message(self, cls, stop_at_brace: bool) -> Message:
        msg = cls()
        fields = {f.name: f for f in cls.FIELDS}
        while True:
            tok = self.peek()
            if tok is None:
                if stop_at_brace:
                    raise ValueError("missing closing '}'")
                return msg
            if tok == ("brace", "}"):
                if not stop_at_brace:
                    raise ValueError("unmatched '}'")
                self.next()
                return msg
            kind, val = self.next()
            if kind != "ident":
                raise ValueError(f"expected field name, got {val!r}")
            f = fields.get(val)
            nxt = self.peek()
            if nxt == ("brace", "{"):
                self.next()
                if f is None:
                    self._skip_block()
                    continue
                if f.kind != "msg":
                    raise ValueError(f"field {val} is not a message")
                sub = self.parse_message(f.msg_cls, stop_at_brace=True)
                self._store(msg, f, sub)
            else:
                if nxt is not None and nxt[0] == "colon":
                    self.next()
                vk, vv = self.next()
                if f is None:
                    continue
                self._store(msg, f, self._convert(f, vk, vv))

    def _skip_block(self):
        depth = 1
        while depth:
            kind, val = self.next()
            if kind == "brace":
                depth += 1 if val == "{" else -1

    @staticmethod
    def _convert(f: Field, kind: str, raw: str) -> Any:
        if f.kind in ("string", "bytes"):
            if kind == "string":
                body = raw[1:-1]
                return (body.encode().decode("unicode_escape")
                        if f.kind == "string" else body.encode())
            return raw
        if f.kind == "bool":
            return raw in ("true", "1", "True")
        if f.kind in ("float", "double"):
            return float(raw)
        if f.kind == "enum":
            if kind == "ident":
                # resolve via class constants (e.g. PoolingParameter.MAX)
                return raw
            return int(raw)
        return int(raw)

    @staticmethod
    def _store(msg: Message, f: Field, val: Any):
        if f.repeated:
            getattr(msg, f.name).append(val)
        else:
            setattr(msg, f.name, val)


def parse(text: str, cls) -> Message:
    """Parse protobuf text format into an instance of ``cls``."""
    return _Parser(_tokenize(text)).parse_message(cls, stop_at_brace=False)


def resolve_enum(owner_cls, value, default: int = 0) -> int:
    """Normalise an enum field that may hold an int or an identifier
    string (text format writes ``pool: MAX``)."""
    if isinstance(value, str):
        return int(getattr(owner_cls, value, default))
    return int(value) if value is not None else default
