"""Caffe layer → TPU-native layer converters.

The reference converts prototxt layers to BigDL modules in
Converter.scala:698 / LayerConverter.scala:792 (V2) and
V1LayerConverter.scala:690 (legacy).  Here each caffe layer becomes a
:class:`FnLayer` (the same fn-layer machinery the ONNX importer uses)
carrying exact Caffe semantics — NCHW layouts, ceil-mode pooling,
pad-inclusive average-pool denominators, BatchNorm scale_factor blobs.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.caffe.caffe_pb import (
    ConvolutionParameter, EltwiseParameter, PoolingParameter)
from analytics_zoo_tpu.models.caffe.prototxt import resolve_enum
from analytics_zoo_tpu.pipeline.api.onnx.mapper import OnnxOp as FnLayer

CONVERTERS: Dict[str, "callable"] = {}


def converts(*types):
    def deco(fn):
        for t in types:
            CONVERTERS[t] = fn
        return fn
    return deco


def _spatial(param, name: str, default: int, n: int = 2) -> List[int]:
    """Resolve caffe's (repeated | _h/_w) spatial params."""
    h = int(getattr(param, name + "_h", 0) or 0)
    w = int(getattr(param, name + "_w", 0) or 0)
    if h or w:
        return [h or default, w or default]
    rep = getattr(param, "kernel_size" if name == "kernel" else name, None)
    if rep is None or rep == [] or rep == 0:
        return [default] * n
    if isinstance(rep, (int, float)):      # pooling params are scalar
        return [int(rep)] * n
    if len(rep) == 1:
        return [int(rep[0])] * n
    return [int(v) for v in rep]


def _filler_init(filler, shape, rng: np.random.RandomState) -> np.ndarray:
    """Materialise a caffe weight_filler/bias_filler when no trained
    blob exists (definition-only loads)."""
    ftype = (filler.type if filler is not None else "constant") or "constant"
    if ftype == "constant":
        return np.full(shape, float(filler.value) if filler else 0.0,
                       dtype=np.float32)
    if ftype == "gaussian":
        return rng.normal(float(filler.mean), float(filler.std or 1.0),
                          shape).astype(np.float32)
    if ftype in ("xavier", "msra"):
        fan_in = int(np.prod(shape[1:])) or 1
        scale = np.sqrt((2.0 if ftype == "msra" else 3.0) / fan_in)
        if ftype == "xavier":
            return rng.uniform(-scale, scale, shape).astype(np.float32)
        return rng.normal(0.0, scale, shape).astype(np.float32)
    if ftype == "uniform":
        return rng.uniform(-1, 1, shape).astype(np.float32)
    return np.zeros(shape, dtype=np.float32)


def _in_channels(t) -> int:
    shape = t.shape
    if len(shape) < 2 or shape[1] is None:
        raise ValueError("cannot infer input channels for weight init")
    return int(shape[1])


@converts("Convolution", "Deconvolution")
def _conv(ctx, layer, blobs, ins):
    p = layer.convolution_param or ConvolutionParameter()
    kernel = _spatial(p, "kernel", 1)
    stride = _spatial(p, "stride", 1)
    pad = _spatial(p, "pad", 0)
    dil = [int(v) for v in (p.dilation or [1])]
    if len(dil) == 1:
        dil = dil * 2
    group = int(p.group or 1)
    deconv = layer.type == "Deconvolution"
    if blobs:
        w = blobs[0]
        bias = blobs[1] if len(blobs) > 1 and p.bias_term else None
    else:
        rng = np.random.RandomState(0)
        cin = _in_channels(ins[0])
        n_out = int(p.num_output)
        wshape = ((cin, n_out, kernel[0], kernel[1]) if deconv
                  else (n_out, cin // group, kernel[0], kernel[1]))
        w = _filler_init(p.weight_filler, wshape, rng)
        bias = (_filler_init(p.bias_filler, (n_out,), rng)
                if p.bias_term else None)
    weights = {"kernel": w}
    if bias is not None:
        weights["bias"] = bias

    if not deconv:
        def fn(prm, xs, training, rng):
            out = jax.lax.conv_general_dilated(
                xs[0], prm["kernel"], window_strides=stride,
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                rhs_dilation=dil, feature_group_count=group,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            if "bias" in prm:
                out = out + prm["bias"].reshape(1, -1, 1, 1)
            return out
    else:
        if group != 1:
            raise NotImplementedError("Deconvolution group>1")
        # caffe deconv weight layout is (in, out, kh, kw)
        weights["kernel"] = np.swapaxes(np.asarray(w), 0, 1)[
            :, :, ::-1, ::-1].copy()

        def fn(prm, xs, training, rng):
            conv_pads = [(dil[i] * (kernel[i] - 1) - pad[i],
                          dil[i] * (kernel[i] - 1) - pad[i])
                         for i in range(2)]
            out = jax.lax.conv_general_dilated(
                xs[0], prm["kernel"], window_strides=[1, 1],
                padding=conv_pads, lhs_dilation=stride, rhs_dilation=dil,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            if "bias" in prm:
                out = out + prm["bias"].reshape(1, -1, 1, 1)
            return out

    return ctx.emit(layer, fn, [ins[0]], weights)


@converts("Pooling")
def _pooling(ctx, layer, blobs, ins):
    p = layer.pooling_param or PoolingParameter()
    mode = resolve_enum(PoolingParameter, p.pool, PoolingParameter.MAX)
    if p.global_pooling:
        if mode == PoolingParameter.AVE:
            return ctx.emit(layer,
                            lambda prm, xs, t, r: jnp.mean(
                                xs[0], axis=(2, 3), keepdims=True),
                            [ins[0]], {})
        return ctx.emit(layer,
                        lambda prm, xs, t, r: jnp.max(
                            xs[0], axis=(2, 3), keepdims=True),
                        [ins[0]], {})
    kernel = _spatial(p, "kernel", 1)
    stride = _spatial(p, "stride", 1)
    pad = _spatial(p, "pad", 0)

    def out_dim(h, i):
        # caffe uses ceil mode; the last window must start inside the
        # padded extent
        o = int(math.ceil((h + 2 * pad[i] - kernel[i]) / stride[i])) + 1
        if pad[i] > 0 and (o - 1) * stride[i] >= h + pad[i]:
            o -= 1
        return o

    def fn(prm, xs, training, rng):
        x = xs[0]
        h, w = x.shape[2], x.shape[3]
        oh, ow = out_dim(h, 0), out_dim(w, 1)
        extra = [max(0, (oh - 1) * stride[0] + kernel[0] - h - 2 * pad[0]),
                 max(0, (ow - 1) * stride[1] + kernel[1] - w - 2 * pad[1])]
        window = (1, 1, kernel[0], kernel[1])
        strd = (1, 1, stride[0], stride[1])
        pads = ((0, 0), (0, 0), (pad[0], pad[0] + extra[0]),
                (pad[1], pad[1] + extra[1]))
        if mode == PoolingParameter.MAX:
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                         window, strd, pads)
        total = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strd,
                                      pads)
        # denominator = overlap with the base-padded extent (caffe
        # counts padding, but not the ceil-mode spill-over region)
        ones = jnp.ones((1, 1, h + 2 * pad[0], w + 2 * pad[1]), x.dtype)
        denom = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, window, strd,
            ((0, 0), (0, 0), (0, extra[0]), (0, extra[1])))
        return total / denom

    return ctx.emit(layer, fn, [ins[0]], {})


@converts("InnerProduct")
def _inner_product(ctx, layer, blobs, ins):
    p = layer.inner_product_param
    axis = int(p.axis if p is not None else 1)
    if blobs:
        w = np.asarray(blobs[0])
        if w.ndim == 4:                   # legacy (1, 1, out, in)
            w = w.reshape(w.shape[-2], w.shape[-1])
        bias = blobs[1] if len(blobs) > 1 and (p is None or p.bias_term) \
            else None
    else:
        rng = np.random.RandomState(0)
        in_dim = 1
        for d in ins[0].shape[axis:]:
            if d is None:
                raise ValueError("cannot infer InnerProduct input dim")
            in_dim *= int(d)
        w = _filler_init(p.weight_filler if p else None,
                         (int(p.num_output), in_dim), rng)
        bias = (_filler_init(p.bias_filler if p else None,
                             (int(p.num_output),), rng)
                if (p is None or p.bias_term) else None)
    weights = {"kernel": w}
    if bias is not None:
        weights["bias"] = np.asarray(bias).reshape(-1)

    def fn(prm, xs, training, rng):
        x = xs[0]
        lead = 1
        for d in x.shape[:axis]:
            lead *= d
        out = x.reshape(lead, -1) @ prm["kernel"].T
        if "bias" in prm:
            out = out + prm["bias"]
        return out

    return ctx.emit(layer, fn, [ins[0]], weights)


@converts("ReLU")
def _relu(ctx, layer, blobs, ins):
    slope = float(layer.relu_param.negative_slope) \
        if layer.relu_param is not None else 0.0
    if slope:
        return ctx.emit(layer,
                        lambda prm, xs, t, r: jnp.where(
                            xs[0] >= 0, xs[0], slope * xs[0]),
                        [ins[0]], {})
    return ctx.emit(layer, lambda prm, xs, t, r: jax.nn.relu(xs[0]),
                    [ins[0]], {})


@converts("PReLU")
def _prelu(ctx, layer, blobs, ins):
    if blobs:
        weights = {"slope": np.asarray(blobs[0]).reshape(-1)}
    else:
        weights = {"slope": np.full(_in_channels(ins[0]), 0.25,
                                    np.float32)}

    def fn(prm, xs, training, rng):
        x = xs[0]
        slope = prm["slope"].reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x >= 0, x, slope * x)

    return ctx.emit(layer, fn, [ins[0]], weights)


@converts("Sigmoid")
def _sigmoid(ctx, layer, blobs, ins):
    return ctx.emit(layer, lambda prm, xs, t, r: jax.nn.sigmoid(xs[0]),
                    [ins[0]], {})


@converts("TanH")
def _tanh(ctx, layer, blobs, ins):
    return ctx.emit(layer, lambda prm, xs, t, r: jnp.tanh(xs[0]),
                    [ins[0]], {})


@converts("AbsVal")
def _absval(ctx, layer, blobs, ins):
    return ctx.emit(layer, lambda prm, xs, t, r: jnp.abs(xs[0]),
                    [ins[0]], {})


@converts("ELU")
def _elu(ctx, layer, blobs, ins):
    alpha = float(layer.elu_param.alpha) if layer.elu_param else 1.0
    return ctx.emit(layer,
                    lambda prm, xs, t, r: jnp.where(
                        xs[0] >= 0, xs[0], alpha * jnp.expm1(xs[0])),
                    [ins[0]], {})


@converts("Power")
def _power(ctx, layer, blobs, ins):
    p = layer.power_param
    power = float(p.power) if p else 1.0
    scale = float(p.scale) if p else 1.0
    shift = float(p.shift) if p else 0.0
    return ctx.emit(layer,
                    lambda prm, xs, t, r: jnp.power(
                        shift + scale * xs[0], power),
                    [ins[0]], {})


@converts("LRN")
def _lrn(ctx, layer, blobs, ins):
    p = layer.lrn_param
    size = int(p.local_size) if p else 5
    alpha = float(p.alpha) if p else 1.0
    beta = float(p.beta) if p else 0.75
    k = float(p.k) if p else 1.0

    def fn(prm, xs, training, rng):
        x = xs[0]
        sq = jnp.square(x)
        lo = (size - 1) // 2
        window = (1, size, 1, 1)
        pad = ((0, 0), (lo, size - 1 - lo), (0, 0), (0, 0))
        ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add, window,
                                     (1, 1, 1, 1), pad)
        return x / jnp.power(k + alpha / size * ssum, beta)

    return ctx.emit(layer, fn, [ins[0]], {})


@converts("BatchNorm")
def _batchnorm(ctx, layer, blobs, ins):
    eps = float(layer.batch_norm_param.eps) \
        if layer.batch_norm_param is not None else 1e-5
    if blobs:
        sf = float(np.asarray(blobs[2]).ravel()[0]) if len(blobs) > 2 else 1.0
        sf = 1.0 / sf if sf != 0 else 0.0
        weights = {"mean": np.asarray(blobs[0]).reshape(-1) * sf,
                   "var": np.asarray(blobs[1]).reshape(-1) * sf}
    else:
        c = _in_channels(ins[0])
        weights = {"mean": np.zeros(c, np.float32),
                   "var": np.ones(c, np.float32)}

    def fn(prm, xs, training, rng):
        x = xs[0]
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return (x - prm["mean"].reshape(shape)) * jax.lax.rsqrt(
            prm["var"].reshape(shape) + eps)

    return ctx.emit(layer, fn, [ins[0]], weights)


@converts("Scale")
def _scale(ctx, layer, blobs, ins):
    p = layer.scale_param
    bias_term = bool(p.bias_term) if p is not None else False
    if blobs:
        weights = {"scale": np.asarray(blobs[0]).reshape(-1)}
        if bias_term and len(blobs) > 1:
            weights["bias"] = np.asarray(blobs[1]).reshape(-1)
    else:
        c = _in_channels(ins[0])
        weights = {"scale": np.ones(c, np.float32)}
        if bias_term:
            weights["bias"] = np.zeros(c, np.float32)

    def fn(prm, xs, training, rng):
        x = xs[0]
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = x * prm["scale"].reshape(shape)
        if "bias" in prm:
            out = out + prm["bias"].reshape(shape)
        return out

    return ctx.emit(layer, fn, [ins[0]], weights)


@converts("Softmax", "SoftmaxWithLoss")
def _softmax(ctx, layer, blobs, ins):
    axis = int(layer.softmax_param.axis) if layer.softmax_param else 1
    return ctx.emit(layer,
                    lambda prm, xs, t, r: jax.nn.softmax(xs[0], axis=axis),
                    [ins[0]], {})


@converts("Dropout")
def _dropout(ctx, layer, blobs, ins):
    ratio = float(layer.dropout_param.dropout_ratio) \
        if layer.dropout_param else 0.5

    def fn(prm, xs, training, rng):
        x = xs[0]
        if not training or rng is None or ratio <= 0:
            return x
        keep = 1.0 - ratio
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    return ctx.emit(layer, fn, [ins[0]], {})


@converts("Concat")
def _concat(ctx, layer, blobs, ins):
    p = layer.concat_param
    axis = int(p.axis) if p is not None else 1

    def fn(prm, xs, training, rng):
        return jnp.concatenate(xs, axis=axis)

    return ctx.emit(layer, fn, list(ins), {})


@converts("Eltwise")
def _eltwise(ctx, layer, blobs, ins):
    p = layer.eltwise_param or EltwiseParameter()
    op = resolve_enum(EltwiseParameter, p.operation, EltwiseParameter.SUM)
    coeff = [float(c) for c in (p.coeff or [])]

    def fn(prm, xs, training, rng):
        if op == EltwiseParameter.PROD:
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if op == EltwiseParameter.MAX:
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        cs = coeff or [1.0] * len(xs)
        out = cs[0] * xs[0]
        for c, x in zip(cs[1:], xs[1:]):
            out = out + c * x
        return out

    return ctx.emit(layer, fn, list(ins), {})


@converts("Flatten")
def _flatten(ctx, layer, blobs, ins):
    axis = int(layer.flatten_param.axis) \
        if getattr(layer, "flatten_param", None) else 1

    def fn(prm, xs, training, rng):
        x = xs[0]
        lead = 1
        for d in x.shape[:axis]:
            lead *= d
        return x.reshape(lead, -1)

    return ctx.emit(layer, fn, [ins[0]], {})


@converts("Reshape")
def _reshape(ctx, layer, blobs, ins):
    shape = [int(d) for d in layer.reshape_param.shape.dim]

    def fn(prm, xs, training, rng):
        x = xs[0]
        tgt = [x.shape[i] if v == 0 else v for i, v in enumerate(shape)]
        return x.reshape(tuple(tgt))

    return ctx.emit(layer, fn, [ins[0]], {})


@converts("Slice")
def _slice(ctx, layer, blobs, ins):
    p = layer.slice_param
    axis = int(p.axis) if p is not None else 1
    points = [int(v) for v in (p.slice_point if p else [])]
    n_out = len(layer.top)

    def fn(prm, xs, training, rng):
        x = xs[0]
        if points:
            return list(jnp.split(x, points, axis=axis))
        return list(jnp.split(x, n_out, axis=axis))

    return ctx.emit(layer, fn, [ins[0]], {}, n_outputs=n_out)


@converts("Split")
def _split(ctx, layer, blobs, ins):
    n_out = len(layer.top)

    def fn(prm, xs, training, rng):
        return [xs[0] for _ in range(n_out)]

    return ctx.emit(layer, fn, [ins[0]], {}, n_outputs=n_out)
