"""Caffe model loader: prototxt + caffemodel → native graph Model.

Parity with ``Net.loadCaffe(defPath, modelPath)``
(pipeline/api/Net.scala:51-190 → models/caffe/CaffeLoader.scala:718):
reads the net definition in protobuf text format and the weights in
binary, converts layers (V1 + V2), and assembles a trainable graph.
Data layers are replaced by graph inputs, in-place layers (top ==
bottom) are chained, and loss/accuracy layers are dropped the way the
reference's ``topologicalSort`` path does.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.caffe import converter as conv_mod
from analytics_zoo_tpu.models.caffe.caffe_pb import (
    LayerParameter, NetParameter, V1LayerParameter)
from analytics_zoo_tpu.models.caffe.prototxt import parse
from analytics_zoo_tpu.pipeline.api.keras.engine import Input, KTensor
from analytics_zoo_tpu.pipeline.api.keras.topology import Model
from analytics_zoo_tpu.pipeline.api.onnx.mapper import OnnxOp as FnLayer

_DATA_LAYERS = {"Data", "ImageData", "HDF5Data", "MemoryData",
                "WindowData", "DummyData", "Input", "AnnotatedData"}
_SKIP_LAYERS = {"Accuracy", "SilenceLayer", "Silence"}

_PARAM_NAMES = [
    "concat_param", "convolution_param", "dropout_param", "eltwise_param",
    "inner_product_param", "lrn_param", "pooling_param", "power_param",
    "relu_param", "softmax_param", "slice_param", "prelu_param",
    "reshape_param", "flatten_param", "batch_norm_param", "elu_param",
    "scale_param", "input_param",
]


def _normalize(layer) -> SimpleNamespace:
    """Uniform view over V1 (enum-typed) and V2 (string-typed) layers."""
    if isinstance(layer, V1LayerParameter):
        type_name = layer.type_name()
    else:
        type_name = layer.type
    ns = SimpleNamespace(
        name=layer.name, type=type_name,
        bottom=list(layer.bottom), top=list(layer.top),
        blobs=list(layer.blobs))
    for p in _PARAM_NAMES:
        setattr(ns, p, getattr(layer, p, None))
    return ns


class _Ctx:
    def __init__(self):
        self._names: Dict[str, int] = {}

    def emit(self, layer, fn, graph_ins: List[KTensor],
             weights: Dict[str, np.ndarray], n_outputs: int = 1):
        base = layer.name or layer.type.lower()
        n = self._names.get(base, 0)
        self._names[base] = n + 1
        name = base if n == 0 else f"{base}_{n}"
        out = FnLayer(fn, weights=weights, n_outputs=n_outputs,
                      name=name)(graph_ins if len(graph_ins) > 1
                                 else graph_ins[0])
        return out if isinstance(out, list) else [out]


class CaffeLoader:
    """``CaffeLoader.load(def_path, model_path)`` → graph ``Model``."""

    @staticmethod
    def load(def_path: str, model_path: Optional[str] = None,
             input_shapes: Optional[Dict[str, Sequence[int]]] = None,
             outputs: Optional[Sequence[str]] = None) -> Model:
        with open(def_path, "r") as f:
            net_def = parse(f.read(), NetParameter)
        weights_by_name: Dict[str, List[np.ndarray]] = {}
        if model_path is not None:
            with open(model_path, "rb") as f:
                net_w = NetParameter.decode(f.read())
            for lyr in list(net_w.layer) + list(net_w.layers):
                if lyr.blobs:
                    weights_by_name[lyr.name] = [b.ndarray()
                                                 for b in lyr.blobs]
        return _build(net_def, weights_by_name, input_shapes or {}, outputs)


def _build(net_def: NetParameter, weights_by_name, input_shapes, outputs):
    tensors: Dict[str, KTensor] = {}
    model_inputs: List[KTensor] = []
    ctx = _Ctx()

    def add_input(name: str, dims: Sequence[int]):
        # caffe shapes are (N, C, H, W); dim 0 is the batch
        t = Input(shape=tuple(int(d) for d in dims[1:]), name=name)
        tensors[name] = t
        model_inputs.append(t)

    # net-level inputs: `input:` + input_shape / legacy input_dim
    if net_def.input:
        for i, name in enumerate(net_def.input):
            if i < len(net_def.input_shape):
                dims = [int(d) for d in net_def.input_shape[i].dim]
            elif net_def.input_dim:
                dims = [int(d) for d in net_def.input_dim[4 * i:4 * i + 4]]
            elif name in input_shapes:
                dims = [0] + list(input_shapes[name])
            else:
                raise ValueError(f"no shape for net input {name!r}")
            add_input(name, dims)

    layers = [_normalize(l) for l in
              (list(net_def.layer) or list(net_def.layers))]

    last_top: Optional[str] = None
    for layer in layers:
        if layer.type in _SKIP_LAYERS:
            continue
        if layer.type in _DATA_LAYERS:
            for top in layer.top:
                if top in ("label",):
                    continue
                if layer.input_param is not None and layer.input_param.shape:
                    dims = [int(d) for d in layer.input_param.shape[0].dim]
                elif top in input_shapes:
                    dims = [0] + list(input_shapes[top])
                else:
                    raise ValueError(
                        f"data layer {layer.name!r}: pass input_shapes="
                        f"{{{top!r}: (C, H, W)}} to define the graph input")
                add_input(top, dims)
            continue
        conv = conv_mod.CONVERTERS.get(layer.type)
        if conv is None:
            raise NotImplementedError(
                f"caffe layer type {layer.type!r} not supported")
        ins = []
        for b in layer.bottom:
            if b == "label":
                continue
            if b not in tensors:
                raise KeyError(f"layer {layer.name}: unknown bottom {b!r}")
            ins.append(tensors[b])
        blobs = weights_by_name.get(layer.name, [b.ndarray()
                                                 for b in layer.blobs])
        outs = conv(ctx, layer, blobs, ins)
        tops = [t for t in layer.top if t != "label"]
        if not tops:
            tops = [layer.name]
        for top, val in zip(tops, outs):
            tensors[top] = val
            last_top = top

    if outputs:
        out_tensors = [tensors[o] for o in outputs]
    else:
        consumed = set()
        for layer in layers:
            if layer.type in _DATA_LAYERS or layer.type in _SKIP_LAYERS:
                continue
            for b in layer.bottom:
                if not (len(layer.top) == 1 and layer.top[0] == b):
                    consumed.add(b)
        leaves = [n for n, t in tensors.items()
                  if n not in consumed and t.node is not None]
        out_tensors = [tensors[n] for n in (leaves or [last_top])]

    return Model(input=model_inputs if len(model_inputs) > 1
                 else model_inputs[0],
                 output=out_tensors if len(out_tensors) > 1
                 else out_tensors[0],
                 name=net_def.name or "caffe_model")


def load_caffe(def_path: str, model_path: Optional[str] = None,
               **kwargs) -> Model:
    """Module-level sugar mirroring ``Net.loadCaffe``."""
    return CaffeLoader.load(def_path, model_path, **kwargs)
