from analytics_zoo_tpu.models.anomalydetection.anomaly_detector import (
    AnomalyDetector, detect_anomalies, unroll,
)

__all__ = ["AnomalyDetector", "detect_anomalies", "unroll"]
