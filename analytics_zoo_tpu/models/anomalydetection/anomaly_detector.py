"""LSTM time-series anomaly detection.

Reference: zoo/models/anomalydetection/AnomalyDetector.scala:40-222 —
stacked LSTMs predicting the next value from an unrolled window;
``Unroll`` builds the windows; ``detectAnomalies`` flags the top-N
largest |y - ŷ| distances as anomalies.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import LSTM


def unroll(data: np.ndarray, unroll_length: int
           ) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: (N, F) series -> x (N-L, L, F), y (N-L,) of the
    value following each window (AnomalyDetector.Unroll)."""
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length
    idx = np.arange(unroll_length)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    y = data[unroll_length:, 0]
    return x, y.reshape(-1, 1)


def detect_anomalies(y_true: np.ndarray, y_pred: np.ndarray,
                     anomaly_size: int = 5) -> np.ndarray:
    """Indices of the ``anomaly_size`` largest absolute errors
    (AnomalyDetector.detectAnomalies)."""
    dist = np.abs(np.ravel(y_true) - np.ravel(y_pred))
    threshold = np.sort(dist)[-anomaly_size]
    return np.where(dist >= threshold)[0]


class AnomalyDetector(ZooModel):
    def __init__(self, feature_shape: Tuple[int, int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        self.feature_shape = tuple(feature_shape)     # (unroll, features)
        self.hidden_layers = list(hidden_layers)
        self.dropouts = list(dropouts)
        assert len(self.hidden_layers) == len(self.dropouts)
        super().__init__()

    def build_model(self):
        inp = Input(shape=self.feature_shape)
        x = inp
        for i, (h, p) in enumerate(zip(self.hidden_layers, self.dropouts)):
            last = (i == len(self.hidden_layers) - 1)
            x = LSTM(h, return_sequences=not last)(x)
            x = Dropout(p)(x)
        out = Dense(1)(x)
        return Model(inp, out)
