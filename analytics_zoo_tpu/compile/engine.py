"""engine_jit — the single lowering chokepoint for every engine-built
jit.

Every compiled program in ``analytics_zoo_tpu/`` (trainer steps, the
estimators, serving/inference predict, utility gathers) is built
through :func:`engine_jit` instead of raw ``jax.jit``/``pjit`` —
enforced by zoolint COMPILE011.  The chokepoint is what makes three
things possible without touching any call site:

* **AOT compilation**: per abstract signature, the wrapper lowers
  once (``jax.jit(...).lower()``), compiles explicitly, and dispatches
  the resulting ``Compiled`` — the pattern from the pjit AOT
  internals (SNIPPETS.md [1]) — instead of relying on the implicit
  per-process jit cache.
* **The persistent executable cache** (:mod:`.cache`): the lowered
  program's content digest addresses an on-disk serialized
  executable; a warm process deserializes in ~seconds where a cold
  one pays the full XLA compile (141s for ResNet-50, BENCH_r05).
* **Warm-start entrypoints**: :meth:`EngineJit.warm` lowers and
  compiles (or cache-loads) ahead of the first dispatch, so
  Estimator/serving pre-pay the compile at startup where it is
  attributable — and a PR 6 re-formed mesh whose signature was seen
  before skips recompilation entirely.

Fallback ladder (never a behavior change, only a speed change):
no cache dir configured → plain ``jax.jit`` dispatch; lowering or
(de)serialization fails → plain jit with a loud counter; a
``Compiled`` rejects its call args (stricter placement rules than
jit's auto-reshard) → that signature permanently falls back to jit.
Execution errors (OOM, collective failures) are NEVER absorbed — they
propagate exactly as the jit path would, so the estimator's recovery
machinery keeps its contract.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Any, Dict, Optional, Set, Tuple

log = logging.getLogger("analytics_zoo_tpu.compile")

_UNSPECIFIED = object()


def _sharding_sig(leaf) -> Any:
    """Canonical, hashable form of a leaf's placement for the call
    signature: named shardings by (mesh shape, spec) — the part that
    determines the compiled program — single-device/uncommitted
    buffers collapse to one bucket (the Compiled call validates the
    actual device; a mismatch falls back per-signature)."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    try:
        from jax.sharding import NamedSharding, SingleDeviceSharding
        if isinstance(sharding, NamedSharding):
            return ("mesh", tuple(sharding.mesh.shape.items()),
                    str(sharding.spec))
        if isinstance(sharding, SingleDeviceSharding):
            # same bucket as sharding-less leaves (ShapeDtypeStruct):
            # a spec-based warm() must produce the signature the
            # concrete first call will look up
            return None
        return repr(sharding)[:120]
    except Exception:   # noqa: BLE001
        return "?"


def call_signature(args: Tuple, static_argnums: Tuple[int, ...] = ()
                   ) -> Tuple:
    """Hashable abstract signature of a call: per argument the pytree
    structure plus (shape, dtype, sharding) per leaf — the same
    shape/dtype keys CompileMonitor and COMPILE003 track, extended
    with placement.  Static positions key on their VALUE (they are
    baked into the program); python scalars elsewhere key on type
    only (weak-typed: the value never retraces)."""
    import jax
    parts = []
    for i, a in enumerate(args):
        if i in static_argnums:
            parts.append(("static", repr(a)))
            continue
        treedef = jax.tree_util.tree_structure(
            a, is_leaf=lambda v: v is None)
        leaves = []
        for leaf in jax.tree_util.tree_leaves(
                a, is_leaf=lambda v: v is None):
            if leaf is None:
                leaves.append(None)
            elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                leaves.append((tuple(leaf.shape), str(leaf.dtype),
                               _sharding_sig(leaf)))
            else:
                leaves.append(("py", type(leaf).__name__))
        parts.append((treedef, tuple(leaves)))
    return tuple(parts)


class EngineJit:
    """A jit-compatible callable with an AOT + persistent-cache fast
    path.  Transparent to wrappers: unknown attributes (``lower``,
    ``eval_shape``, ...) forward to the underlying jitted function,
    so ``CompileMonitor.wrap`` and ``benchmarks.compiled_flops`` keep
    working on it unchanged."""

    def __init__(self, fn, *, static_argnums=(), donate_argnums=(),
                 in_shardings=_UNSPECIFIED,
                 out_shardings=_UNSPECIFIED,
                 key_hint: Optional[str] = None):
        import jax
        if isinstance(static_argnums, int):
            static_argnums = (static_argnums,)
        kwargs: Dict[str, Any] = {
            "static_argnums": tuple(static_argnums),
            "donate_argnums": donate_argnums,
        }
        if in_shardings is not _UNSPECIFIED:
            kwargs["in_shardings"] = in_shardings
        if out_shardings is not _UNSPECIFIED:
            kwargs["out_shardings"] = out_shardings
        self._fn = fn
        self._jit = jax.jit(fn, **kwargs)
        self._static = tuple(static_argnums)
        self._donate = donate_argnums
        self.key_hint = key_hint or getattr(fn, "__qualname__", None) \
            or getattr(fn, "__name__", None) or "fn"
        #: signature -> live jax.stages.Compiled
        self._compiled: Dict[Tuple, Any] = {}
        #: the ONE live executable while exactly one signature exists —
        #: the hot-path shortcut: dispatch it optimistically without
        #: recomputing the call signature (a whole-pytree walk; params
        #: can be thousands of leaves).  The Compiled validates its
        #: args BEFORE executing/donating, so shape/dtype/placement
        #: drift raises cleanly into the slow path instead of running
        #: wrong.  Cleared the moment a second signature (or any
        #: fallback) appears.
        self._solo: Optional[Any] = None
        #: signatures permanently routed to the plain jit path
        self._fallback: Set[Tuple] = set()
        self._cache = _UNSPECIFIED   # resolved lazily on first call
        self._lock = threading.Lock()

    # ------------------------------------------------------------ plumbing
    def __getattr__(self, item):
        return getattr(self._jit, item)

    def _resolve_cache(self):
        if self._cache is _UNSPECIFIED:
            try:
                from analytics_zoo_tpu.compile.cache import get_cache
                self._cache = get_cache()
            except Exception:   # noqa: BLE001
                self._cache = None
        return self._cache

    def _signature(self, args) -> Optional[Tuple]:
        try:
            return call_signature(args, self._static)
        except Exception:   # noqa: BLE001 — unhashable exotic args
            return None

    def _dynamic_args(self, args) -> Tuple:
        """Static positions are baked into the Compiled — drop them."""
        if not self._static:
            return args
        return tuple(a for i, a in enumerate(args)
                     if i not in self._static)

    def _aot_enabled(self) -> bool:
        """The ``compile.aot`` kill switch: False must disable the
        WHOLE AOT path — including warm()/aot(), which otherwise
        compile and install a Compiled that __call__ would then
        dispatch (the documented contract is 'off = plain jax.jit
        dispatch')."""
        try:
            from analytics_zoo_tpu.common.config import get_config
            return bool(get_config().get("compile.aot", True))
        except Exception:   # noqa: BLE001
            return True

    def _monitor(self):
        try:
            from analytics_zoo_tpu.observability.diagnostics import (
                get_compile_monitor)
            return get_compile_monitor()
        except Exception:   # noqa: BLE001
            return None

    # ---------------------------------------------------------------- AOT
    def _cache_key(self, lowered, sig) -> Optional[str]:
        from analytics_zoo_tpu.compile.cache import cache_key
        try:
            hlo = lowered.as_text()
        except Exception:   # noqa: BLE001
            return None
        return cache_key(
            hashlib.sha256(hlo.encode()).hexdigest(),
            repr(sig), donate_repr=repr(self._donate),
            static_repr=repr(self._static))

    def _acquire(self, args, sig, persist: bool = True):
        """Load-or-compile the executable for ``sig``: lower, look the
        content key up in the persistent cache, deserialize on hit,
        compile (and persist) on miss.  Returns None when the AOT path
        is unavailable for these args (caller falls back to jit)."""
        cache = self._resolve_cache()
        monitor = self._monitor()
        t0 = time.perf_counter()
        try:
            lowered = self._jit.lower(*args)
        except Exception:   # noqa: BLE001 — fall back, don't guess
            log.debug("engine_jit %r: lowering failed; plain jit path",
                      self.key_hint, exc_info=True)
            return None
        key = None
        if cache is not None:
            key = self._cache_key(lowered, sig)
            if key is not None:
                exe = cache.load(key)
                if exe is not None:
                    if monitor is not None:
                        monitor.record_cache_event(
                            self.key_hint, hit=True,
                            seconds=time.perf_counter() - t0)
                    log.info(
                        "engine_jit %r: executable cache HIT "
                        "(%.2fs load, key %s...)", self.key_hint,
                        time.perf_counter() - t0, key[:12])
                    return exe
            if monitor is not None:
                monitor.record_cache_event(self.key_hint, hit=False)
        try:
            exe = lowered.compile()
        except Exception:   # noqa: BLE001
            log.debug("engine_jit %r: AOT compile failed; plain jit "
                      "path", self.key_hint, exc_info=True)
            return None
        if cache is not None and key is not None and persist:
            cache.store(key, exe, key_hint=self.key_hint)
        return exe

    # --------------------------------------------------------------- calls
    def __call__(self, *args):
        # static-argnum programs never take the shortcut: a changed
        # static VALUE leaves the dynamic avals identical, so the
        # Compiled's validation could not catch the drift and would
        # silently run the old baked-in constant
        exe = self._solo if not self._static else None
        if exe is not None:
            try:
                return exe(*args)
            except (TypeError, ValueError):
                # signature drift (or a genuinely bad call): recompute
                # the signature on the slow path, which compiles the
                # new shape or surfaces the real error via plain jit.
                # Validation raises BEFORE execution/donation, so the
                # caller's buffers are intact for the retry.
                pass
        return self._call_slow(*args)

    def _call_slow(self, *args):
        cache = self._resolve_cache()
        if cache is None and not self._compiled:
            return self._jit(*args)
        sig = self._signature(args)
        if sig is None or sig in self._fallback:
            return self._jit(*args)
        exe = self._compiled.get(sig)
        if exe is None:
            if cache is None:
                return self._jit(*args)
            with self._lock:
                exe = self._compiled.get(sig)
                if exe is None:
                    exe = self._acquire(args, sig)
                    if exe is None:
                        # zoolint: disable=ATOM017 — the unlocked guard at the top of _call_slow is a fast-path skip; set.add is idempotent, so two threads passing it merely both mark the same sig
                        self._fallback.add(sig)
                        self._solo = None
                        return self._jit(*args)
                    self._compiled[sig] = exe
                    self._solo = exe if len(self._compiled) == 1 \
                        else None
        try:
            return exe(*self._dynamic_args(args))
        except (TypeError, ValueError):
            # a Compiled validates placement strictly where jit would
            # auto-reshard (e.g. a committed arg on an unexpected
            # device); validation raises BEFORE execution/donation, so
            # the plain jit retry sees intact buffers.  Execution
            # errors are other types and propagate above.
            log.warning(
                "engine_jit %r: compiled executable rejected its call "
                "args; this signature falls back to the plain jit "
                "path", self.key_hint, exc_info=True)
            from analytics_zoo_tpu.compile.cache import _count_error
            _count_error("call")
            with self._lock:
                # eviction after the executable itself raised: keyed on
                # the exception, not on the earlier (unlocked fast-path)
                # cache probes, and add/pop-with-default are idempotent
                # zoolint: disable=ATOM017 — idempotent eviction, not a stale-guard decision
                self._fallback.add(sig)
                # zoolint: disable=ATOM017 — idempotent eviction, not a stale-guard decision
                self._compiled.pop(sig, None)
                self._solo = None
            return self._jit(*args)

    # ---------------------------------------------------------- warm-start
    def aot(self, *args):
        """Load-or-compile the AOT executable for these args and
        return the live ``jax.stages.Compiled`` — for callers that
        hold the compiled object directly (the bench's
        ``epoch_fn.lower().compile()`` idiom, which would bypass the
        persistent cache).  Falls back to a direct lower+compile when
        the AOT path is unavailable for these args, so it always
        returns a Compiled.  Remember statics are baked in: call the
        result with the dynamic args only."""
        if not self._aot_enabled():
            return self._jit.lower(*args).compile()
        sig = self._signature(args)
        if sig is not None and sig not in self._fallback:
            with self._lock:
                exe = self._compiled.get(sig)
                if exe is None:
                    exe = self._acquire(args, sig)
                    if exe is not None:
                        self._compiled[sig] = exe
                        self._solo = exe if len(self._compiled) == 1 \
                            else None
            if exe is not None:
                return exe
        return self._jit.lower(*args).compile()

    def warm(self, *args) -> bool:
        """AOT warm-start: ensure an executable for this signature is
        ready — deserialized from the persistent cache or compiled now
        (and persisted) — WITHOUT executing anything.  ``args`` may be
        concrete arrays or ``jax.ShapeDtypeStruct``s (with shardings
        attached for sharded programs).  Never donates, never runs a
        step.  Returns whether the AOT executable is in place."""
        if not self._aot_enabled():
            return False
        sig = self._signature(args)
        if sig is None or sig in self._fallback:
            return False
        if sig in self._compiled:
            return True
        with self._lock:
            if sig in self._compiled:
                return True
            exe = self._acquire(args, sig)
            if exe is None:
                return False
            self._compiled[sig] = exe
            self._solo = exe if len(self._compiled) == 1 else None
        return True

    @property
    def aot_signatures(self) -> int:
        """How many signatures currently dispatch through an AOT
        executable (introspection for tests/diagnostics)."""
        return len(self._compiled)


def engine_jit(fn, *, static_argnums=(), donate_argnums=(),
               in_shardings=_UNSPECIFIED, out_shardings=_UNSPECIFIED,
               key_hint: Optional[str] = None) -> EngineJit:
    """Build a compiled callable through the platform chokepoint —
    the drop-in replacement for every ``jax.jit``/``pjit`` site in
    ``analytics_zoo_tpu/`` (zoolint COMPILE011 enforces this).

    Semantics match ``jax.jit(fn, static_argnums=..., donate_argnums=
    ..., in_shardings=..., out_shardings=...)`` exactly; ``key_hint``
    names the program in cache metadata and the
    ``compile_cache_hits_total{fn=...}`` counters.
    """
    return EngineJit(fn, static_argnums=static_argnums,
                     donate_argnums=donate_argnums,
                     in_shardings=in_shardings,
                     out_shardings=out_shardings, key_hint=key_hint)
