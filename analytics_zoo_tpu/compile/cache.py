"""Content-addressed persistent executable cache.

BENCH_r05 measured ResNet-50 spending ~141s compiling for ~2.3s of
timed compute per repeat, and every serving replica spawn, elastic
mesh re-formation (PR 6), and bench round pays the same cold-start
tax again.  The reference platform amortizes setup across a
long-lived Spark cluster (BigDL, arXiv:1804.05839); here a compiled
XLA executable becomes a *cached, shippable artifact* instead of a
per-process toll.

Layout: one file per entry, ``<cache_dir>/<key>.zooexec``, where
``key`` is a content digest over

* the lowered StableHLO text (subsumes the jaxpr, baked static-arg
  values, sharding annotations and mesh partitioning),
* the abstract call signature (shapes / dtypes / shardings / pytree
  structure — the same information CompileMonitor and COMPILE003 key
  recompiles on),
* backend platform + device kind + device/process counts (mesh
  geometry beyond what the HLO encodes),
* XLA_FLAGS, and the donation/static-argnum spec.

jax/jaxlib/backend *versions* deliberately live in the entry's META,
not the key: a version bump finds the old entry, evicts it LOUDLY
(``compile_cache_errors_total{kind="stale"}``), and recompiles —
rather than silently stranding unreachable files until the LRU sweep.

Durability contract:

* writes are atomic (same-directory temp file + ``os.replace``), so
  two processes racing on one key — the compile-farm case — can never
  tear an entry; last writer wins with identical content;
* loads are corruption-safe: any unreadable/undeserializable/stale
  entry is a MISS plus a loud counter and eviction, never a crash;
* the directory honors a size cap with LRU eviction
  (``compile.cache_max_mb``, ``compile_cache_evictions_total``).

Compile-farm mode: when no explicit cache dir is configured but the
process runs inside a launcher ``run_dir`` (the PR 4 env contract),
the cache lands in ``<run_dir>/compile-cache`` and only host 0
persists entries — workers deserialize host-0's executables instead
of recompiling.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.compile")

#: explicit cache-dir override (takes precedence over config); the
#: same variable bench.py --compile-cache and the Jenkins test lanes
#: export
ENV_CACHE_DIR = "ZOO_TPU_COMPILE_CACHE"

ENTRY_SUFFIX = ".zooexec"


def _counter(name: str, doc: str, labels=()):
    from analytics_zoo_tpu.observability import get_registry
    return get_registry().counter(name, doc, labels=labels)


def _count_error(kind: str) -> None:
    """Loud-counter contract: every bad/stale/unwritable entry is
    visible on /metrics, never silently absorbed."""
    try:
        _counter(
            "compile_cache_errors_total",
            "executable-cache entries rejected or failed, by kind "
            "(corrupt/stale/io/serialize/call)",
            labels=("kind",)).labels(kind).inc()
    except Exception:   # noqa: BLE001 — metrics never block the cache
        pass


def backend_signature() -> str:
    """Platform + device kind + device/process counts — the part of
    the mesh geometry the HLO text alone does not pin down."""
    import jax
    dev = jax.devices()[0]
    return "|".join((
        getattr(dev, "platform", "?"),
        str(getattr(dev, "device_kind", "?")),
        str(jax.device_count()),
        str(jax.process_count()),
    ))


def runtime_versions() -> Dict[str, str]:
    """The version triple checked (loudly) at LOAD time — an entry
    serialized by a different jax/jaxlib/backend build is evicted, not
    trusted."""
    import jax
    import jaxlib
    try:
        backend = jax.devices()[0].client.platform_version
    except Exception:   # noqa: BLE001 — version probe must not raise
        backend = "?"
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": str(backend)}


def cache_key(hlo_digest: str, signature_repr: str,
              donate_repr: str = "()", static_repr: str = "()",
              backend_sig: Optional[str] = None,
              xla_flags: Optional[str] = None) -> str:
    """Content digest of everything that determines the executable.

    Shape/dtype/static-arg/sharding changes land in ``hlo_digest`` and
    ``signature_repr``; mesh changes land in both the HLO partitioning
    and ``backend_sig``; donation is keyed explicitly because aliasing
    must match the caller's buffer expectations even where a backend
    elides it from the IR text.
    """
    if backend_sig is None:
        backend_sig = backend_signature()
    if xla_flags is None:
        xla_flags = os.environ.get("XLA_FLAGS", "")
    material = "\x1f".join((hlo_digest, signature_repr, donate_repr,
                            static_repr, backend_sig, xla_flags))
    return hashlib.sha256(material.encode()).hexdigest()


def _process_id() -> int:
    """Worker index for the farm write policy: the launcher env
    contract first (works before/without jax.distributed), the live
    jax process index second."""
    raw = os.environ.get("ZOO_TPU_PROCESS_ID")
    if raw is not None:
        try:
            return int(raw)
        except ValueError:
            pass
    try:
        import jax
        return jax.process_index()
    except Exception:   # noqa: BLE001
        return 0


def resolve_cache_dir() -> Optional[Tuple[str, bool]]:
    """``(cache_dir, farm_mode)`` or None when caching is off.

    Precedence: ``ZOO_TPU_COMPILE_CACHE`` env > ``compile.cache_dir``
    config > (``compile.farm``) the launcher run-dir slot
    ``<ZOO_TPU_RUN_DIR>/compile-cache``.
    """
    env = os.environ.get(ENV_CACHE_DIR, "").strip()
    if env:
        return env, False
    from analytics_zoo_tpu.common.config import get_config
    cfg = get_config()
    explicit = str(cfg.get("compile.cache_dir") or "").strip()
    if explicit:
        return explicit, False
    if bool(cfg.get("compile.farm", True)):
        run_dir = os.environ.get("ZOO_TPU_RUN_DIR", "").strip()
        if run_dir:
            return os.path.join(run_dir, "compile-cache"), True
    return None


class _StaleEntry(RuntimeError):
    pass


class ExecutableCache:
    """On-disk executable store with atomic writes, corruption-safe
    loads, and an LRU size cap.  One instance per directory per
    process (see :func:`get_cache`); safe under concurrent processes
    because every mutation is a whole-file rename or unlink."""

    def __init__(self, cache_dir: str, max_mb: Optional[float] = None,
                 write_enabled: bool = True):
        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        if max_mb is None:
            try:
                from analytics_zoo_tpu.common.config import get_config
                max_mb = float(get_config().get(
                    "compile.cache_max_mb", 2048))
            except Exception:   # noqa: BLE001
                max_mb = 2048.0
        self.max_bytes = int(max_mb * (1 << 20)) if max_mb > 0 else 0
        self.write_enabled = bool(write_enabled)
        self._lock = threading.Lock()

    # --------------------------------------------------------------- paths
    def path_for(self, key: str) -> str:
        return os.path.join(self.dir, key + ENTRY_SUFFIX)

    def entries(self) -> List[str]:
        try:
            return sorted(f for f in os.listdir(self.dir)
                          if f.endswith(ENTRY_SUFFIX))
        except OSError:
            return []

    # ---------------------------------------------------------------- load
    def load(self, key: str):
        """Deserialize the entry for ``key`` into a live
        ``jax.stages.Compiled``, or None (miss).  A present-but-bad
        entry — torn write, hand-edit, version skew — is EVICTED with
        a loud counter and becomes a miss; it can never crash the
        caller or poison a training step."""
        path = self.path_for(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                doc = pickle.load(f)
            meta = doc["meta"]
            current = runtime_versions()
            if meta.get("versions") != current:
                raise _StaleEntry(
                    f"entry built by {meta.get('versions')}, running "
                    f"{current}")
            from jax.experimental import serialize_executable as se
            exe = se.deserialize_and_load(*doc["payload"])
        except _StaleEntry as e:
            # read-only processes (farm workers, cache_write=false)
            # must never mutate the shared directory: a worker on a
            # skewed jax build unlinking host-0's valid entry would
            # cold-start every SAME-version peer on the fleet.  For
            # them a stale entry is just a miss; the writer evicts.
            log.warning(
                "compile cache: %s VERSION-STALE entry %s (%s); "
                "treating as a miss",
                "evicting" if self.write_enabled else "ignoring",
                os.path.basename(path), e)
            _count_error("stale")
            if self.write_enabled:
                self._evict_file(path)
            return None
        except Exception:   # noqa: BLE001 — corrupt-entry contract
            log.warning(
                "compile cache: %s unreadable/corrupt entry %s; "
                "treating as a miss",
                "evicting" if self.write_enabled else "ignoring",
                os.path.basename(path), exc_info=True)
            _count_error("corrupt")
            if self.write_enabled:
                self._evict_file(path)
            return None
        if self.write_enabled:
            try:
                os.utime(path, None)   # LRU recency on hit
            except OSError:
                pass
        return exe

    # --------------------------------------------------------------- store
    def store(self, key: str, compiled, key_hint: str = "") -> bool:
        """Serialize + persist atomically (write-then-rename): a
        concurrent writer on the same key — two farm hosts, two bench
        children — cannot tear the entry; both produce identical
        content and the last rename wins.  Returns whether the entry
        landed.  Backends that cannot serialize executables degrade to
        False with a loud counter (the in-memory AOT executable still
        serves this process)."""
        if not self.write_enabled:
            return False
        try:
            from jax.experimental import serialize_executable as se
            payload = se.serialize(compiled)
            blob = pickle.dumps({
                "meta": {
                    "versions": runtime_versions(),
                    "key_hint": key_hint,
                    "created_unix": round(time.time(), 1),
                },
                "payload": payload,
            })
        except Exception:   # noqa: BLE001 — non-serializing backend
            log.warning(
                "compile cache: backend cannot serialize executable "
                "for %r; entry not persisted (in-memory AOT still "
                "active)", key_hint or key, exc_info=True)
            _count_error("serialize")
            return False
        path = self.path_for(key)
        try:
            fd, tmp = tempfile.mkstemp(
                dir=self.dir, prefix=".tmp-" + key[:16] + "-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)   # atomic on one filesystem
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception:   # noqa: BLE001 — full disk, permissions...
            log.warning("compile cache: could not persist entry %s",
                        os.path.basename(path), exc_info=True)
            _count_error("io")
            return False
        try:
            _counter("compile_cache_writes_total",
                     "executable-cache entries persisted").inc()
        except Exception:   # noqa: BLE001
            pass
        self._enforce_cap()
        return True

    # ------------------------------------------------------------ eviction
    def _evict_file(self, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def _enforce_cap(self) -> None:
        """LRU sweep: drop oldest-by-mtime entries until the directory
        fits ``compile.cache_max_mb``.  mtime is bumped on every hit,
        so recency ordering is true LRU across processes sharing the
        directory."""
        if self.max_bytes <= 0:
            return
        with self._lock:
            try:
                stats = []
                for name in self.entries():
                    p = os.path.join(self.dir, name)
                    try:
                        st = os.stat(p)
                    except OSError:
                        continue
                    stats.append((st.st_mtime, st.st_size, p))
                total = sum(s[1] for s in stats)
                if total <= self.max_bytes:
                    return
                stats.sort()   # oldest first
                evicted = 0
                for mtime, size, p in stats:
                    if total <= self.max_bytes:
                        break
                    self._evict_file(p)
                    total -= size
                    evicted += 1
                if evicted:
                    log.info(
                        "compile cache: LRU-evicted %d entr%s to fit "
                        "the %.0f MB cap (%s)", evicted,
                        "y" if evicted == 1 else "ies",
                        self.max_bytes / (1 << 20), self.dir)
                    try:
                        _counter(
                            "compile_cache_evictions_total",
                            "executable-cache entries LRU-evicted to "
                            "honor compile.cache_max_mb").inc(evicted)
                    except Exception:   # noqa: BLE001
                        pass
            except Exception:   # noqa: BLE001 — the sweep is advisory
                log.debug("compile cache: LRU sweep failed",
                          exc_info=True)


# ------------------------------------------------------------- singleton
_caches: Dict[str, ExecutableCache] = {}
_caches_lock = threading.Lock()


def get_cache() -> Optional[ExecutableCache]:
    """The process cache for the currently-resolved directory, or None
    when AOT caching is off (no dir configured, or ``compile.aot``
    false).  Farm mode (run-dir-derived dir) enables writes on host 0
    only; everyone reads."""
    try:
        from analytics_zoo_tpu.common.config import get_config
        cfg = get_config()
        if not bool(cfg.get("compile.aot", True)):
            return None
        resolved = resolve_cache_dir()
        if resolved is None:
            return None
        cache_dir, farm = resolved
        cache_dir = os.path.abspath(cache_dir)
        with _caches_lock:
            cache = _caches.get(cache_dir)
            if cache is None:
                write = bool(cfg.get("compile.cache_write", True)) and \
                    (not farm or _process_id() == 0)
                cache = ExecutableCache(cache_dir, write_enabled=write)
                _caches[cache_dir] = cache
        return cache
    except Exception:   # noqa: BLE001 — cache resolution must never
        log.debug("compile cache resolution failed", exc_info=True)
        return None     # break a training/serving path


def reset_cache_state() -> None:
    """Drop the per-directory cache singletons (test helper — config
    or write-policy changes take effect on the next resolve)."""
    with _caches_lock:
        _caches.clear()
