"""AOT compilation + persistent executable cache.

``engine_jit`` is the single lowering chokepoint every compiled
program in ``analytics_zoo_tpu/`` is built through (zoolint COMPILE011
enforces it); :mod:`.cache` turns compiled XLA executables into
content-addressed on-disk artifacts so a warm process deserializes in
seconds where a cold one pays the full compile (141s for ResNet-50,
BENCH_r05).  See docs/aot-compile.md.
"""

from analytics_zoo_tpu.compile.cache import (  # noqa: F401
    ENV_CACHE_DIR, ExecutableCache, backend_signature, cache_key,
    get_cache, reset_cache_state, resolve_cache_dir, runtime_versions)
from analytics_zoo_tpu.compile.engine import (  # noqa: F401
    EngineJit, call_signature, engine_jit)
