"""zoolint v2 rule families — the graph-powered checks.

These rules consume what the interprocedural layer (``project.py``)
computed: the mesh-axis universe, cross-module jitted callables, the
call graph and the lock summaries.  Catalog (docs/static-analysis.md
renders the full entries with their runtime-diagnostics twins):

=========  ==========================================================
SHARD007   PartitionSpec propagation: axis names absent from every
           mesh in the project, full replication of large params
           under shard_map, spec churn in hot loops, conflicting
           sharding constraints — runtime twin: PR 4's
           ``collective_bytes_total{op}`` counters
           (``zoolint --explain-comms`` prices the traffic with the
           same ring identities)
MEM009     static HBM live-buffer hazards: state rebound through a
           non-donating jit call site (both copies live), device
           results accumulated unboundedly in hot loops — runtime
           twin: device telemetry gauges
           (``zoolint --explain-hbm`` prices the step peak)
LOCK010    lock-order/deadlock analysis over the thread-running
           modules: lock-acquisition graph cycles (inconsistent
           order), re-acquisition of a non-reentrant lock through a
           call chain, locks held across blocking calls — runtime
           twin: PR 3's stall watchdog
=========  ==========================================================
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Finding, ModuleContext, Rule, _dotted, donated_positions,
    register_rule)
from analytics_zoo_tpu.analysis.project import (
    CANONICAL_AXES, FuncKey, ProjectContext, register_project_rule)

# ================================================================ SHARD007


_PSPEC_NAMES = ("jax.sharding.PartitionSpec",
                "jax.experimental.pjit.PartitionSpec",
                "PartitionSpec")
_NAMED_SHARDING_NAMES = ("jax.sharding.NamedSharding", "NamedSharding")
_SHARD_MAP_NAMES = ("jax.shard_map", "shard_map",
                    "jax.experimental.shard_map.shard_map")
_WSC_NAMES = ("jax.lax.with_sharding_constraint",
              "with_sharding_constraint",
              "jax.experimental.pjit.with_sharding_constraint")

#: parameter names whose full replication is worth flagging
_LARGE_PARAM_RE = re.compile(
    r"(?:^|_)(params?|weights?|table|embeddings?|kernel|w\d?|"
    r"opt_state|state)s?$")


def _is_pspec_call(ctx: ModuleContext, node: ast.Call) -> bool:
    name = ctx.resolve(node.func)
    return name in _PSPEC_NAMES or (
        name is not None and name.endswith(".PartitionSpec"))


@register_rule
class ShardSpecRule(Rule):
    """PartitionSpec propagation checks.

    Why: GSPMD trusts the annotation.  A typo'd axis name raises only
    when the program finally runs on a mesh; a ``P()`` on a large
    param under ``shard_map`` silently replicates it onto every
    device; a spec constructed per hot-loop iteration churns
    placement; two different constraints on one value force a
    reshard.  All four are invisible until the job is on real
    hardware — exactly what a static pass is for.  The runtime twin
    is PR 4's ``collective_bytes_total{op}`` accounting; ``zoolint
    --explain-comms`` prices the implied traffic with the same ring
    identities so static and measured numbers join.
    """

    rule_id = "SHARD007"
    severity = "warning"
    doc = ("sharding-spec hazard: unknown mesh axis, implicit full "
           "replication, spec churn in a hot loop, or conflicting "
           "constraints")

    def begin_module(self, ctx: ModuleContext) -> None:
        super().begin_module(ctx)
        self._wsc_seen: Dict[Tuple[int, str], Tuple[str, ast.AST]] = {}

    # -- axis universe ---------------------------------------------------
    def _universe(self, ctx: ModuleContext) -> Set[str]:
        if ctx.axis_universe:
            return ctx.axis_universe
        return set(CANONICAL_AXES)

    def _axis_of(self, ctx: ModuleContext,
                 node: ast.AST) -> Optional[str]:
        """The axis STRING an expression denotes, when statically
        known: a literal, or a ``*_AXIS`` constant the project
        indexed.  None = unverifiable (a variable)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        resolved = _dotted(node) and ctx.resolve(node)
        if resolved:
            if resolved in ctx.axis_constants:
                return ctx.axis_constants[resolved]
            local = f"{ctx.module_name}.{resolved}"
            if local in ctx.axis_constants:
                return ctx.axis_constants[local]
        return None

    def _check_axes(self, ctx: ModuleContext, call: ast.Call,
                    exprs) -> None:
        universe = self._universe(ctx)
        for expr in exprs:
            parts = expr.elts if isinstance(
                expr, (ast.Tuple, ast.List)) else [expr]
            for part in parts:
                axis = self._axis_of(ctx, part)
                if axis is not None and axis not in universe:
                    self.report(
                        call,
                        f"PartitionSpec axis '{axis}' is not an axis "
                        f"of any mesh in this project (known: "
                        f"{', '.join(sorted(universe))}) — GSPMD "
                        f"will reject it at run time",
                        line=getattr(part, "lineno", call.lineno))

    # -- visitors --------------------------------------------------------
    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        name = ctx.resolve(node.func) or ""
        if _is_pspec_call(ctx, node):
            self._check_axes(ctx, node, node.args)
            self._check_hot_loop_spec(ctx, node, "PartitionSpec")
            return
        if name in _NAMED_SHARDING_NAMES or \
                name.endswith(".NamedSharding"):
            self._check_hot_loop_spec(ctx, node, "NamedSharding")
            return
        if name in _SHARD_MAP_NAMES:
            self._check_shard_map(ctx, node)
            return
        if name in _WSC_NAMES:
            self._check_constraint(ctx, node)

    def _check_hot_loop_spec(self, ctx: ModuleContext, node: ast.Call,
                             what: str) -> None:
        """Spec/sharding objects built per iteration of a host-side
        hot loop: every construction is a fresh object, and a placed
        array gets resharded when the spec drifts — hoist it.
        Lexical loops only: a helper that builds one spec per CALL is
        priced at its call site, not here."""
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) in ctx.traced_functions:
            return
        if not ctx.is_hot_function(fn):
            return
        if not ctx.in_loop(node, lexical_only=True):
            return
        self.report(
            node,
            f"{what} constructed inside a hot loop — build the spec "
            f"once outside the loop (a drifting spec implicitly "
            f"reshards every iteration)")

    def _check_shard_map(self, ctx: ModuleContext,
                         node: ast.Call) -> None:
        in_specs = None
        for kw in node.keywords:
            if kw.arg == "in_specs":
                in_specs = kw.value
            if kw.arg in ("in_specs", "out_specs"):
                specs = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                for spec in specs:
                    if isinstance(spec, ast.Call) and \
                            _is_pspec_call(ctx, spec):
                        self._check_axes(ctx, node, spec.args)
        if in_specs is None or not node.args:
            return
        fn = ctx._wrapped_function(node.args[0], node)
        params = ProjectContext.func_params_of_node(fn)
        specs = in_specs.elts if isinstance(
            in_specs, (ast.Tuple, ast.List)) else [in_specs]
        for i, spec in enumerate(specs):
            if not (isinstance(spec, ast.Call)
                    and _is_pspec_call(ctx, spec)
                    and not spec.args and not spec.keywords):
                continue
            pname = params[i] if i < len(params) else f"arg{i}"
            if _LARGE_PARAM_RE.search(pname):
                self.report(
                    node,
                    f"shard_map arg '{pname}' has in_spec P() — the "
                    f"full array is replicated onto every device; "
                    f"shard it over a mesh axis (or confirm it is "
                    f"small and suppress)",
                    line=spec.lineno)

    def _check_constraint(self, ctx: ModuleContext,
                          node: ast.Call) -> None:
        """Two different with_sharding_constraint specs on the same
        name inside one traced function = a forced mid-program
        reshard."""
        if not node.args or not isinstance(node.args[0], ast.Name) or \
                len(node.args) < 2:
            return
        fn = ctx.enclosing_function(node)
        if fn is None:
            return
        try:
            spec_src = ast.unparse(node.args[1])
        except Exception:
            return
        key = (id(fn), node.args[0].id)
        prev = self._wsc_seen.get(key)
        if prev is None:
            self._wsc_seen[key] = (spec_src, node)
        elif prev[0] != spec_src and not self._exclusive_branches(
                ctx, prev[1], node):
            self.report(
                node,
                f"'{node.args[0].id}' already constrained to "
                f"{prev[0]} at line {prev[1].lineno} — a different "
                f"spec here forces a reshard between the two points")

    @staticmethod
    def _exclusive_branches(ctx: ModuleContext, a: ast.AST,
                            b: ast.AST) -> bool:
        """Do ``a`` and ``b`` sit in OPPOSITE arms of some shared
        ``if``?  Then only one executes per trace — two different
        constraints there are a dispatch, not a reshard."""

        def arms(node: ast.AST) -> Dict[int, str]:
            out: Dict[int, str] = {}
            prev, cur = node, ctx.parent(node)
            while cur is not None:
                if isinstance(cur, ast.If):
                    if any(c is prev for c in cur.body):
                        out[id(cur)] = "body"
                    elif any(c is prev for c in cur.orelse):
                        out[id(cur)] = "orelse"
                prev, cur = cur, ctx.parent(cur)
            return out

        arms_a = arms(a)
        return any(side != arms_a.get(if_id, side)
                   for if_id, side in arms(b).items())


# ================================================================= MEM009


_STATE_NAME_RE = re.compile(
    r"^(?:new_)?(params?|opt_states?|optimizer_state|state|weights?|"
    r"variables|grads?|master_params)$")


def _bound_names_of_targets(targets) -> Set[str]:
    # one binding-target walker for the whole rule set
    from analytics_zoo_tpu.analysis.rules import KeyReuseRule
    out: Set[str] = set()
    for t in targets:
        out |= KeyReuseRule._bound_names(t)
    return out


@register_rule
class HbmLiveBufferRule(Rule):
    """Static HBM live-buffer hazards.

    Why: HBM is the scarcest resource on the chip.  (1) A jit call
    whose state inputs die at the call (``params, opt_state =
    step(params, opt_state, ...)``) but whose jit declares no
    donation keeps BOTH trees live through the step — double the
    largest arrays in the program (this generalizes DONATE004 from
    the jit's own signature to any call site of any jitted
    callable).  (2) A hot loop appending jitted outputs to a plain
    list pins every step's device buffers forever — the OOM arrives
    hours in.  Runtime twin: the device telemetry gauges
    (``device_memory_bytes``/live-array census); ``zoolint
    --explain-hbm`` prices the step peak statically.
    """

    rule_id = "MEM009"
    severity = "warning"
    doc = ("HBM hazard: non-donated dead state at a jit call site, "
           "or unbounded device-array accumulation in a hot loop")

    # -- (1) dead state through a non-donating jit ----------------------
    def visit_Assign(self, node: ast.Assign,
                     ctx: ModuleContext) -> None:
        value = node.value
        if not isinstance(value, ast.Call):
            return
        target = _dotted(value.func)
        if target is None or target not in ctx.jitted_callables:
            return
        kws = ctx.jitted_callables[target]
        donated = self._donated_positions(kws)
        if donated is None:
            return   # argnames / non-literal argnums: assume covered
        bound = _bound_names_of_targets(node.targets)
        for pos, arg in enumerate(value.args):
            if pos in donated:
                continue
            if isinstance(arg, ast.Name) and arg.id in bound and \
                    _STATE_NAME_RE.match(arg.id):
                self.report(
                    node,
                    f"'{arg.id}' dies at this call (rebound by the "
                    f"result) but jitted '{target}' does not donate "
                    f"it (no donate_argnums covering position {pos}) "
                    f"— input and output copies stay live together "
                    f"through the step (double HBM for the biggest "
                    f"arrays)")
                return

    _donated_positions = staticmethod(donated_positions)

    # -- (2) unbounded device accumulation in hot loops ------------------
    _GROW_METHODS = ("append", "extend")

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._GROW_METHODS
                and len(node.args) == 1
                and isinstance(node.func.value, ast.Name)):
            return
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) in ctx.traced_functions:
            return
        if not ctx.is_hot_function(fn) or not ctx.in_loop(node):
            return
        if not self._is_device_valued(ctx, fn, node.args[0],
                                      node.lineno):
            return
        lname = node.func.value.id
        if self._is_bounded(ctx, fn, lname):
            return
        self.report(
            node,
            f"'{lname}.{node.func.attr}(...)' accumulates device "
            f"results every iteration with no bound or host pull — "
            f"each step's output stays pinned in HBM; pull to host "
            f"(jax.device_get) or keep a bounded window")

    def _is_device_valued(self, ctx: ModuleContext, fn: ast.AST,
                          expr: ast.AST,
                          use_line: Optional[int] = None) -> bool:
        """Does ``expr`` denote the output of a jitted/traced
        callable?  Direct call, or a name whose binding in ``fn`` is
        such a call — precision over recall.  For a name, the binding
        that REACHES the use site is the latest one before
        ``use_line`` (``x = step(...); x = np.asarray(x)`` appends a
        host array, not the jitted output); with none before, the
        last binding overall (a loop's append sees the previous
        iteration's final value)."""
        if isinstance(expr, ast.Call):
            target = _dotted(expr.func)
            if target in ctx.jitted_callables:
                return True
            wrapped = ctx._wrapped_function(expr.func, expr)
            return wrapped is not None and \
                id(wrapped) in ctx.traced_functions
        if isinstance(expr, (ast.Subscript, ast.Attribute)):
            return False
        if isinstance(expr, ast.Name):
            before: Optional[ast.Assign] = None
            last: Optional[ast.Assign] = None
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            queue: List[ast.AST] = list(body)
            i = 0
            while i < len(queue):
                node = queue[i]
                i += 1
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                queue.extend(ast.iter_child_nodes(node))
                if not isinstance(node, ast.Assign):
                    continue
                if expr.id not in _bound_names_of_targets(
                        node.targets):
                    continue
                if last is None or node.lineno >= last.lineno:
                    last = node
                if use_line is not None and node.lineno < use_line \
                        and (before is None
                             or node.lineno >= before.lineno):
                    before = node
            pick = before if before is not None else last
            if pick is not None and isinstance(pick.value, ast.Call):
                return self._is_device_valued(ctx, fn, pick.value)
            return False
        return False

    @staticmethod
    def _is_bounded(ctx: ModuleContext, fn: ast.AST,
                    lname: str) -> bool:
        """Evidence the accumulator is bounded or drained: a
        ``len(lname)`` flush check, a ``deque(maxlen=...)`` binding,
        an explicit ``clear``/``pop``/``del``, or a host pull
        (device_get / np.asarray) that references it."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = ctx.resolve(node.func) or ""
                if name == "len" and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id == lname:
                    return True
                if name.split(".")[-1] in ("device_get", "asarray",
                                           "array", "stack",
                                           "concatenate"):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and \
                                sub.id == lname:
                            return True
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("clear", "popleft", "pop") \
                        and isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == lname:
                    return True
                if name.split(".")[-1] == "deque":
                    par = ctx.parent(node)
                    if isinstance(par, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == lname
                            for t in par.targets):
                        return True
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name) and \
                                sub.id == lname:
                            return True
        return False


# ================================================================ LOCK010


_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "rlock",
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
}

#: resolved call names that block the calling thread
_BLOCKING_RESOLVED = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_output", "subprocess.check_call",
    "select.select", "socket.create_connection",
    "jax.block_until_ready", "jax.device_get",
}

#: attribute-call names that block (with the precision guards applied
#: in ``_blocking_desc``)
_BLOCKING_ATTRS = {
    "wait", "communicate", "blpop", "brpop", "brpoplpush",
    "xread", "xreadgroup", "block_until_ready", "accept", "recv",
}


class _FnLockSummary:
    __slots__ = ("edges", "blocking", "calls_under", "acquired",
                 "blocks_desc")

    def __init__(self):
        # (held_id, acquired_id, site)
        self.edges: List[Tuple[str, str, ast.AST]] = []
        # (held_id, site, description)
        self.blocking: List[Tuple[str, ast.AST, str]] = []
        # (held_id, callee FuncKey, site)
        self.calls_under: List[Tuple[str, FuncKey, ast.AST]] = []
        self.acquired: Set[str] = set()
        self.blocks_desc: Optional[str] = None   # fn blocks directly


@register_project_rule
class LockOrderRule:
    """Lock-order / deadlock analysis over thread-running modules.

    Why: the observability aggregator, serving loop, launcher and
    resilience machinery all hold locks from multiple threads.  Two
    locks taken in opposite orders on two threads deadlock — a hang
    with no traceback, which PR 3's stall watchdog can only report
    *after* the job froze.  This pass builds the lock-acquisition
    graph (``with`` nesting plus acquisitions reached through
    resolvable calls), flags cycles, non-reentrant re-acquisition
    through a call chain, and locks held across blocking calls
    (``queue.get``, redis reads, ``subprocess.wait``, device syncs)
    — the pattern that turns one slow consumer into a cluster-wide
    stall.  Scoped to modules that define locks.
    """

    rule_id = "LOCK010"
    severity = "warning"
    doc = ("lock-order cycle, non-reentrant re-acquisition, or lock "
           "held across a blocking call")

    # ------------------------------------------------------------ locks
    def _lock_registry(self, ctx: ModuleContext) -> Dict[str, str]:
        """lock id -> kind for every lock the module defines.
        Module-level ``X = threading.Lock()`` ->
        ``relpath::X``; ``self.X = threading.Lock()`` inside class C
        -> ``relpath::C.X`` (one id per class attribute: standard
        instance-insensitive lock analysis).

        Memoized on the ctx: the lock-order rule, the race index and
        the publication rule all need this table, and the full AST
        walk per module is the single hottest loop in the gate."""
        cached = getattr(ctx, "_zoolint_lock_reg", None)
        if cached is not None:
            return cached
        reg: Dict[str, str] = {}
        for node in ctx.all_nodes:
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            resolved = ctx.resolve(node.value.func)
            kind = _LOCK_CTORS.get(resolved or "")
            if kind is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        ctx.enclosing_function(node) is None:
                    reg[f"{ctx.relpath}::{tgt.id}"] = kind
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    cls = ctx.enclosing_class(node)
                    if cls is not None:
                        reg[f"{ctx.relpath}::"
                            f"{ctx.class_qualname(cls)}."
                            f"{tgt.attr}"] = kind
        ctx._zoolint_lock_reg = reg
        return reg

    def _lock_id(self, ctx: ModuleContext, registry: Dict[str, str],
                 expr: ast.AST, origin: ast.AST,
                 proj: Optional[ProjectContext] = None
                 ) -> Optional[str]:
        """Resolve a ``with`` target / receiver to a lock id.  Bare
        names only count when they denote MODULE state: a lock-ish
        name bound locally (``my_lock = threading.Lock()`` inside the
        function) is a fresh per-call object that cannot deadlock
        across functions — aliasing those by name minted false
        order-cycle pairs.  An IMPORTED lock's identity is its
        DEFINING module — per-importer ids would split one lock into
        many (false self-deadlocks on re-entry, an order cycle across
        two importers never connecting into one graph node)."""
        if isinstance(expr, ast.Call):
            return None
        if isinstance(expr, ast.Name):
            mid = f"{ctx.relpath}::{expr.id}"
            if mid in registry:
                return mid
            resolved = ctx.resolve(expr)
            if proj is not None and resolved and "." in resolved:
                parts = resolved.split(".")
                for i in range(len(parts) - 1, 0, -1):
                    mctx = proj.by_module.get(".".join(parts[:i]))
                    if mctx is not None:
                        return (f"{mctx.relpath}::"
                                f"{'.'.join(parts[i:])}")
            if "lock" in expr.id.lower():
                from analytics_zoo_tpu.analysis.rules import (
                    _local_bindings)
                fn = ctx.enclosing_function(origin)
                if fn is not None and expr.id in _local_bindings(fn):
                    return None   # function-local lock object
                return mid
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls"):
            cls = ctx.enclosing_class(origin)
            if cls is None:
                return None
            cid = f"{ctx.relpath}::" \
                  f"{ctx.class_qualname(cls)}.{expr.attr}"
            if cid in registry or "lock" in expr.attr.lower():
                return cid
        return None

    # ------------------------------------------------------- summaries
    def _blocking_desc(self, ctx: ModuleContext, registry,
                       call: ast.Call,
                       held: List[str],
                       origin_fn: ast.AST,
                       proj: Optional[ProjectContext] = None
                       ) -> Optional[Tuple[str, Optional[str]]]:
        """(description, released_lock_id) for a blocking call.
        ``released_lock_id`` is the one lock the call itself lets go
        of while waiting — a Condition's own lock during ``.wait()``
        (the cv idiom) — which must not be reported as held across
        it; every OTHER held lock stays held for the whole wait."""
        resolved = ctx.resolve(call.func)
        if resolved in _BLOCKING_RESOLVED:
            return (resolved, None)
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr == "get":
            # queue.Queue.get() / .get(block=, timeout=) blocks;
            # dict.get(key[, default]) never has ZERO positional args
            if not call.args and all(
                    kw.arg in ("block", "timeout")
                    for kw in call.keywords):
                return (".get() (queue)", None)
            return None
        if attr == "join":
            # thread/process/queue join blocks; ''.join(seq) takes a
            # positional argument
            return (".join() (thread/queue)", None) \
                if not call.args else None
        if attr == "result":
            return (".result() (future)", None) \
                if not call.args else None
        if attr in _BLOCKING_ATTRS:
            if attr == "wait":
                rid = self._lock_id(ctx, registry, call.func.value,
                                    origin_fn, proj)
                if rid is not None and registry.get(rid) == \
                        "condition":
                    return (".wait() (condition)", rid)
            return (f".{attr}()", None)
        return None

    def _summarize(self, proj: ProjectContext, ctx: ModuleContext,
                   registry: Dict[str, str], fn: ast.AST,
                   key: FuncKey) -> _FnLockSummary:
        s = _FnLockSummary()

        def walk(node: ast.AST, held: List[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return   # nested scope: summarized on its own
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired_here: List[str] = []
                for item in node.items:
                    lid = self._lock_id(ctx, registry,
                                        item.context_expr, node, proj)
                    if lid is not None:
                        s.acquired.add(lid)
                        for h in held:
                            s.edges.append((h, lid, node))
                        acquired_here.append(lid)
                for child in node.body:
                    walk(child, held + acquired_here)
                return
            if isinstance(node, ast.Call):
                if held:
                    res = self._blocking_desc(ctx, registry, node,
                                              held, fn, proj)
                    if res is not None:
                        desc, released = res
                        # EVERY held lock (except the one the call
                        # releases) stays held for the whole wait —
                        # reporting only the innermost would go green
                        # after fixing the inner scope while an outer
                        # (e.g. global) lock still stalls the world
                        for h in dict.fromkeys(held):
                            if h != released:
                                s.blocking.append((h, node, desc))
                        # callers holding THEIR lock across a call to
                        # this function stall the same way (a cv
                        # wait releases only the cv — the thread
                        # still blocks)
                        if s.blocks_desc is None:
                            s.blocks_desc = desc
                    callee = proj.resolve_call(ctx, node)
                    if callee is not None:
                        for h in dict.fromkeys(held):
                            s.calls_under.append((h, callee, node))
                elif s.blocks_desc is None:
                    res = self._blocking_desc(ctx, registry, node,
                                              held, fn, proj)
                    if res is not None:
                        s.blocks_desc = res[0]
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            walk(stmt, [])
        return s

    # ----------------------------------------------------------- driver
    def check_project(self, proj: ProjectContext) -> List[Finding]:
        registries = {ctx.relpath: self._lock_registry(ctx)
                      for ctx in proj.contexts}
        kinds: Dict[str, str] = {}
        for reg in registries.values():
            kinds.update(reg)
        summaries: Dict[FuncKey, _FnLockSummary] = {}
        for ctx in proj.contexts:
            registry = registries[ctx.relpath]
            # only modules that define (or name) locks participate —
            # but summaries resolve against the MERGED kind map, so an
            # imported lock's kind (rlock/condition) is known here too
            if not registry and "lock" not in ctx.source.lower():
                continue
            for fn in ctx.functions:
                if isinstance(fn, ast.Lambda):
                    continue
                qual = ctx.qualname_of(fn)
                if not qual:
                    continue
                summaries[(ctx.relpath, qual)] = self._summarize(
                    proj, ctx, kinds, fn, (ctx.relpath, qual))

        # transitive lock sets + does-it-block, to fixpoint
        acquired_star: Dict[FuncKey, Set[str]] = {
            k: set(s.acquired) for k, s in summaries.items()}
        blocks: Dict[FuncKey, Optional[str]] = {
            k: s.blocks_desc for k, s in summaries.items()}
        changed = True
        while changed:
            changed = False
            for key, s in summaries.items():
                for edge in proj.calls.get(key, ()):
                    sub = acquired_star.get(edge.callee)
                    if sub and not sub <= acquired_star[key]:
                        acquired_star[key] |= sub
                        changed = True
                    # does-it-block propagates too: calling a function
                    # that (transitively) blocks IS blocking — this is
                    # what lets ``with lock: a()`` fire when a() only
                    # reaches the sleep/get through another hop
                    cal_blocks = blocks.get(edge.callee)
                    if cal_blocks and not blocks.get(key):
                        blocks[key] = (f"{cal_blocks} "
                                       f"(via {edge.callee[1]})")
                        changed = True

        findings: List[Finding] = []

        def emit(ctx: ModuleContext, site: ast.AST,
                 message: str) -> None:
            findings.append(Finding(
                rule=self.rule_id, severity=self.severity,
                path=ctx.relpath, line=site.lineno,
                col=getattr(site, "col_offset", 0), message=message,
                symbol=ctx.qualname_of(site),
                snippet=ctx.line_text(site.lineno).strip()))

        # interprocedural edges + held-across-blocking-call findings
        all_edges: List[Tuple[str, str, ast.AST, str]] = []
        for key, s in summaries.items():
            ctx = proj.by_relpath[key[0]]
            for held, lid, site in s.edges:
                all_edges.append((held, lid, site, key[0]))
            for held, site, desc in s.blocking:
                if kinds.get(held) == "semaphore":
                    continue   # a semaphore BRACKETING slow work is a
                    # throttle, not a mutex held across I/O
                emit(ctx, site,
                     f"'{_short(held)}' is held across blocking "
                     f"{desc} — every thread needing the lock "
                     f"stalls behind the wait (runtime twin: the "
                     f"stall watchdog)")
            for held, callee, site in s.calls_under:
                sub = acquired_star.get(callee, set())
                for lid in sorted(sub):
                    all_edges.append((held, lid, site, key[0]))
                cal_blocks = blocks.get(callee)
                if cal_blocks and kinds.get(held) != "semaphore":
                    emit(ctx, site,
                         f"'{_short(held)}' is held across a call "
                         f"to {callee[1]} which blocks on "
                         f"{cal_blocks}")

        # graph analysis: self-loops (non-reentrant) and order cycles
        graph: Dict[str, Set[str]] = {}
        for a, b, _, _ in all_edges:
            graph.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen: Set[str] = set()
            stack = [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(graph.get(cur, ()))
            return False

        reported: Set[Tuple[str, int, str, str]] = set()
        for a, b, site, rel in all_edges:
            ctx = proj.by_relpath[rel]
            dedup = (rel, site.lineno, a, b)
            if dedup in reported:
                continue
            if a == b:
                if kinds.get(a, "lock") in ("rlock", "condition",
                                            "semaphore"):
                    continue
                reported.add(dedup)
                emit(ctx, site,
                     f"non-reentrant '{_short(a)}' is re-acquired "
                     f"while already held (directly or through this "
                     f"call) — self-deadlock")
            elif reaches(b, a):
                reported.add(dedup)
                emit(ctx, site,
                     f"'{_short(b)}' acquired while holding "
                     f"'{_short(a)}', but elsewhere the acquisition "
                     f"order is reversed — inconsistent lock order "
                     f"across threads can deadlock")
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]
