"""zoolint baseline + diff gating.

The baseline is the repo's **acknowledged debt**: findings that
predate the linter (or are accepted with reason) keyed by
:meth:`Finding.key` — file + rule + enclosing symbol + source text —
so unrelated line drift never invalidates it.  Contract:

- a finding **not** covered by the baseline fails the run (exit 1);
- a baseline entry **no longer matched** also fails the run — the
  baseline may only shrink.  Fixing a finding without removing its
  entry would otherwise leave a slot a future regression could hide
  in;
- ``pre_fix_total`` records how many findings the very first run of
  zoolint saw before this PR fixed the true positives; the tier-1
  test asserts the checked-in baseline stays strictly below it.

``--diff BASE.json`` is the lighter PR gate: compare against a
previous ``--json`` dump and fail only on NEW findings — no full
baseline rewrite needed on a feature branch.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from analytics_zoo_tpu.analysis.core import Finding

BASELINE_VERSION = 1


def count_by_key(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        k = f.key()
        out[k] = out.get(k, 0) + 1
    return out


def load_baseline(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a zoolint baseline "
                         f"(missing 'findings')")
    return data


def write_baseline(path: str, findings: List[Finding],
                   pre_fix_total: int = None) -> Dict:
    """Serialize the current findings as the new baseline.  Entries
    keep a human-readable locator next to each opaque key so a
    reviewer can see what debt an entry stands for."""
    keys = count_by_key(findings)
    where: Dict[str, str] = {}
    for f in findings:
        where.setdefault(
            f.key(), f"{f.path}:{f.symbol or '<module>'}: "
                     f"{f.rule} {f.snippet[:80]}")
    data = {
        "version": BASELINE_VERSION,
        "pre_fix_total": (pre_fix_total if pre_fix_total is not None
                          else len(findings)),
        "total": len(findings),
        "findings": {k: {"count": n, "where": where[k]}
                     for k, n in sorted(keys.items(),
                                        key=lambda kv: where[kv[0]])},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")
    return data


def _entry_count(entry) -> int:
    # accept both {"count": n, ...} entries and bare ints
    if isinstance(entry, dict):
        return int(entry.get("count", 1))
    return int(entry)


def apply_baseline(findings: List[Finding], baseline: Dict
                   ) -> Tuple[List[Finding], List[str]]:
    """Partition current findings against a baseline.

    Returns ``(new_findings, stale_entries)``: findings beyond each
    key's baselined count, and baseline entries matched by FEWER
    current findings than recorded (fixed code whose entry must now be
    dropped — the only-shrink rule)."""
    allowed = {k: _entry_count(v)
               for k, v in baseline.get("findings", {}).items()}
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        if seen[k] > allowed.get(k, 0):
            new.append(f)
    stale: List[str] = []
    for k, n in allowed.items():
        have = seen.get(k, 0)
        if have < n:
            entry = baseline["findings"][k]
            where = entry.get("where", k) if isinstance(entry, dict) \
                else k
            stale.append(
                f"baseline entry no longer matched ({have}/{n} "
                f"remain): {where}")
    return new, stale


def diff_findings(findings: List[Finding], base_report: Dict
                  ) -> List[Finding]:
    """New findings relative to a previous ``--json`` report (the
    ``--diff BASE.json`` PR gate).  Counted per key, so adding a
    second identical violation to an already-dirty line still
    fails."""
    allowed: Dict[str, int] = {}
    for item in base_report.get("findings", []):
        k = item["key"]
        allowed[k] = allowed.get(k, 0) + 1
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        if seen[k] > allowed.get(k, 0):
            new.append(f)
    return new
