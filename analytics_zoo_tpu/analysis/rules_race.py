"""zoolint v4 rule families — thread-role + lockset race analysis.

Built on the PR 7 project graph (now carrying thread-role inference:
``proj.thread_roles`` attributes every function to the set of thread
roles that may execute it) and the LOCK010 lock-identity machinery.
Catalog (docs/static-analysis.md renders the full entries with their
runtime twins):

==========  =========================================================
RACE016     an instance attribute written on one thread role and
            read/written on another with DISJOINT locksets — the
            cross-thread data race class that is CPU-silent and
            device-fatal (a skewed donated buffer, a stomped slot
            free-list).  Exemptions: pre-``start()`` initialization,
            ``queue.Queue``/``Event``/lock-typed attributes,
            single-writer atomic-swap / monotonic-flag idioms
            (plain write on one role, plain read on another) —
            runtime twin: zoo-racecheck
ATOM017     check-then-act on shared instance state under
            inconsistent locks (``if self.x: ... self.x = y`` where
            the guard and the write hold different locksets — the
            PR 12 ``_backlog_seen`` registry-gauge-stomping class)
            — runtime twin: the serving gauge/readiness metrics
PUBLISH018  unsafe publication: an object handed to another thread
            (Thread target/args, ``queue.put``, callback/registry
            registration) while the constructing method keeps
            mutating it un-locked — the consumer can observe the
            half-initialized object — runtime twin: the
            flight-recorder ``replica.spawn`` ordering
WRITE019    non-atomic ``open(path, "w")`` to a run-dir-shared path
            — a concurrent reader (obs_report, zoo-doctor, a peer
            worker) sees a torn file; route through
            ``common.fsutil.atomic_write_text``/``_bytes``
==========  =========================================================

RACE016/ATOM017 are project rules: they need the cross-module call
graph (roles propagate through it; locksets inherited from callers
are the intersection over all call sites).  PUBLISH018/WRITE019 are
module rules with cheap source pre-filters, so the full-repo gate
stays inside the PR 14 wall-time envelope.
"""

from __future__ import annotations

import ast
from typing import (
    Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple)

from analytics_zoo_tpu.analysis.cfg import (
    EXC, FALSE, TRUE, CFGNode, State, run_forward)
from analytics_zoo_tpu.analysis.core import (
    Finding, ModuleContext, Rule, _dotted, register_rule)
from analytics_zoo_tpu.analysis.project import (
    FuncKey, ProjectContext, register_project_rule)
from analytics_zoo_tpu.analysis.rules import _MUTATING_METHODS
from analytics_zoo_tpu.analysis.rules_flow import (
    _cfg_for, _functions, _walk_evaluated)
from analytics_zoo_tpu.analysis.rules_graph import LockOrderRule, _short

# ------------------------------------------------------------ access kinds

READ = "read"
WRITE = "write"       # plain ``self.x = v`` rebind
RMW = "rmw"           # ``self.x += v`` read-modify-write
MUT = "mut"           # in-place mutation: item store, ``.append()``…

_STRENGTH = {READ: 0, WRITE: 1, RMW: 2, MUT: 3}
_KIND_DESC = {READ: "read", WRITE: "assigned", RMW: "read-modified",
              MUT: "mutated in place"}

#: roles that name MANY concurrent threads even alone (executor pools)
_MULTI_ROLES = frozenset({"pool"})

#: attribute types that carry their own synchronization — handing
#: state through these IS the sanctioned cross-thread idiom
_SYNC_CTORS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "multiprocessing.Queue",
    "collections.deque", "deque",
    "threading.Event", "Event", "threading.Barrier", "Barrier",
    "threading.local", "concurrent.futures.ThreadPoolExecutor",
    "ThreadPoolExecutor", "concurrent.futures.ProcessPoolExecutor",
})

#: thread-spawning constructors (mirrors the role-inference pass's
#: set in project.py — kept in lockstep by test fixtures)
_SPAWN_CTORS = frozenset({
    "threading.Thread", "Thread", "threading.Timer", "Timer",
    "_thread.start_new_thread",
})

#: methods that publish a callable to another thread — the cutoff for
#: the pre-``start()`` initialization exemption
_SPAWNING_ATTRS = ("start", "submit")
_SPAWNING_RESOLVED = ("_thread.start_new_thread", "atexit.register",
                      "signal.signal")

#: method names whose body up to the first spawn is single-threaded
#: construction (nothing else can hold the instance yet)
_INIT_METHODS = ("__init__", "__post_init__")
_START_METHODS = ("start", "open", "launch", "run_background")


# --------------------------------------------------------------- helpers


def _self_base(expr: ast.AST) -> Optional[str]:
    """The innermost ``self.X`` attribute of an attribute/subscript
    chain (``self.X.y[k]`` → ``X``); None when the chain is not
    rooted at ``self``."""
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        if isinstance(cur, ast.Attribute) and \
                isinstance(cur.value, ast.Name) and \
                cur.value.id == "self":
            return cur.attr
        cur = cur.value
    return None


def _store_accesses(t: ast.AST) -> List[Tuple[str, str]]:
    """(kind, attr) for every ``self``-rooted piece of a binding
    target: direct ``self.X = …`` is a WRITE (rebind — candidates for
    the atomic-swap exemption); ``self.X[k] = …`` / ``self.X.y = …``
    mutate the object X already holds."""
    out: List[Tuple[str, str]] = []
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            out.extend(_store_accesses(e))
    elif isinstance(t, ast.Starred):
        out.extend(_store_accesses(t.value))
    elif isinstance(t, ast.Attribute) and \
            isinstance(t.value, ast.Name) and t.value.id == "self":
        out.append((WRITE, t.attr))
    else:
        base = _self_base(t)
        if base is not None:
            out.append((MUT, base))
    return out


def _fn_accesses(fn: ast.AST) -> Dict[Tuple[str, int],
                                      Tuple[str, ast.AST, bool]]:
    """(attr, line) -> (strongest kind, site node, constant-write?)
    for every ``self.attr`` access lexically in ``fn`` (nested
    defs/lambdas pruned — they are separate role-attributed
    functions)."""
    roots = fn.body if isinstance(fn.body, list) else [fn.body]
    accs: Dict[Tuple[str, int], Tuple[str, ast.AST, bool]] = {}

    def add(kind: str, attr: str, node: ast.AST,
            const: bool = False) -> None:
        key = (attr, getattr(node, "lineno", 0))
        cur = accs.get(key)
        if cur is None or _STRENGTH[kind] > _STRENGTH[cur[0]]:
            accs[key] = (kind, node, const)
        elif cur is not None and kind == cur[0] == WRITE:
            accs[key] = (kind, cur[1], cur[2] and const)

    for node in _walk_evaluated(roots):
        if isinstance(node, ast.Assign):
            const = isinstance(node.value, ast.Constant)
            for t in node.targets:
                for kind, attr in _store_accesses(t):
                    add(kind, attr, t, const and kind == WRITE)
        elif isinstance(node, ast.AugAssign):
            t = node.target
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self":
                add(RMW, t.attr, t)
            else:
                base = _self_base(t)
                if base is not None:
                    add(MUT, base, t)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                for kind, attr in _store_accesses(t):
                    add(MUT if kind == WRITE else kind, attr, t)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _MUTATING_METHODS:
                base = _self_base(f.value)
                if base is not None:
                    add(MUT, base, node)
        elif isinstance(node, ast.Attribute) and \
                isinstance(getattr(node, "ctx", None), ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            add(READ, node.attr, node)
    return accs


def _locks_at(ctx: ModuleContext, registry: Dict[str, str],
              node: ast.AST, proj: Optional[ProjectContext],
              lockrule: LockOrderRule) -> FrozenSet[str]:
    """Lock ids structurally held at ``node``: every enclosing
    ``with`` item that resolves through LOCK010's identity machinery.
    A node inside a ``with`` ITEM (the acquisition expression itself)
    does not yet hold that item's lock."""
    out: Set[str] = set()
    cur: ast.AST = node
    parent = ctx.parent(cur)
    while parent is not None and not isinstance(
            parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.Lambda, ast.ClassDef, ast.Module)):
        if isinstance(parent, (ast.With, ast.AsyncWith)) and \
                not isinstance(cur, ast.withitem):
            for item in parent.items:
                lid = lockrule._lock_id(ctx, registry,
                                        item.context_expr, node, proj)
                if lid is not None:
                    out.add(lid)
        cur, parent = parent, ctx.parent(parent)
    return frozenset(out)


def _mentions_self(nodes: Iterable[ast.AST],
                   methods: Optional[Set[str]] = None) -> bool:
    """Does handing these expressions to a thread publish ``self``?
    A bare ``self`` does; a bound method (``self._loop``) does — the
    callee gets the instance through ``__self__``.  A plain attribute
    handle (``self.engine``) hands only THAT object: the receiving
    thread never sees our instance, so it is not a capture."""
    for n in nodes:
        bases: Set[int] = set()    # attr bases are not bare mentions
        for sub in ast.walk(n):    # parents precede children
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id == "self":
                bases.add(id(sub.value))
                if methods is None or sub.attr in methods:
                    return True
            elif isinstance(sub, ast.Name) and sub.id == "self" \
                    and id(sub) not in bases:
                return True
    return False


def _call_inputs(call: ast.Call) -> List[ast.AST]:
    return list(call.args) + [kw.value for kw in call.keywords]


def _spawn_captures(ctx: ModuleContext, scope: ast.AST,
                    methods: Optional[Set[str]]) -> Dict[str, bool]:
    """dotted target -> did its spawn-ctor assignment capture
    ``self``?  (``self._t = Thread(target=self._loop)`` → True;
    engines/transports built without a back-ref → absent)."""
    out: Dict[str, bool] = {}
    roots = scope.body if isinstance(scope.body, list) else [scope.body]
    for node in _walk_evaluated(roots):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        if (ctx.resolve(node.value.func) or "") not in _SPAWN_CTORS:
            continue
        cap = _mentions_self(_call_inputs(node.value), methods)
        for t in node.targets:
            d = _dotted(t)
            if d:
                out[d] = cap
    return out


def _spawn_lines(ctx: ModuleContext, fn: ast.AST) -> List[int]:
    """Linenos where this method hands THIS instance to another
    thread — the cutoff after which ``self`` is visible cross-thread.
    A ``.start()``/``.submit()`` only counts when ``self`` escapes
    through it: a bound method / bare ``self`` in the args, or a
    thread constructed (here or anywhere in the class) with a
    ``self``-capturing target.  ``self.engine.start()`` spawns the
    ENGINE's thread — that thread never sees our instance, so
    construction after it is still single-threaded."""
    roots = fn.body if isinstance(fn.body, list) else [fn.body]
    cls = ctx.enclosing_class(fn)
    methods: Optional[Set[str]] = None
    if cls is not None:
        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
    captures = _spawn_captures(ctx, fn, methods)
    if cls is not None:
        cls_caps = _spawn_captures(ctx, cls, methods)
        cls_caps.update(captures)      # method-local wins
        captures = cls_caps
    lines: List[int] = []
    for node in _walk_evaluated(roots):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SPAWNING_ATTRS:
            publishes = _mentions_self(_call_inputs(node), methods)
            recv = f.value
            if isinstance(recv, ast.Call):     # Thread(...).start()
                publishes = publishes or _mentions_self(
                    _call_inputs(recv), methods)
            else:
                d = _dotted(recv)
                if d is not None:
                    publishes = publishes or captures.get(d, False)
            if publishes:
                lines.append(node.lineno)
        elif (ctx.resolve(f) or "") in _SPAWNING_RESOLVED:
            if _mentions_self(_call_inputs(node), methods):
                lines.append(node.lineno)
    return lines


def _prestart_cutoff(ctx: ModuleContext, fn: ast.AST,
                     qual: str) -> Optional[int]:
    """The lineno BELOW which accesses in this method run before the
    instance is published to any thread: in ``__init__`` the whole
    method when it spawns nothing (a sentinel of -1 → everything is
    exempt), else up to the first spawn; in ``start()``-shaped
    methods only up to the first spawn."""
    tail = qual.rsplit(".", 1)[-1]
    if tail in _INIT_METHODS:
        spawns = _spawn_lines(ctx, fn)
        return min(spawns) if spawns else -1
    if tail in _START_METHODS:
        spawns = _spawn_lines(ctx, fn)
        return min(spawns) if spawns else None
    return None


def _sync_attrs(ctx: ModuleContext) -> Dict[str, Set[str]]:
    """class qualname -> attrs assigned from a synchronized type
    (queues, events, deques, executors, thread-locals) anywhere in
    the module — these carry their own cross-thread contract.
    Memoized on the ctx: the race index, ATOM017 and PUBLISH018 all
    consult the same table."""
    cached = getattr(ctx, "_zoolint_sync_attrs", None)
    if cached is not None:
        return cached
    out: Dict[str, Set[str]] = {}
    for node in ctx.all_nodes:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # self.q: deque = deque()
            targets = [node.target]
        else:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        resolved = ctx.resolve(node.value.func) or ""
        if resolved not in _SYNC_CTORS:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self":
                cls = ctx.enclosing_class(node)
                if cls is not None:
                    out.setdefault(ctx.class_qualname(cls),
                                   set()).add(t.attr)
    ctx._zoolint_sync_attrs = out
    return out


# ---------------------------------------------------- shared access index


class _Access:
    __slots__ = ("kind", "node", "line", "col", "locks", "roles",
                 "qual", "prestart", "const")

    def __init__(self, kind: str, node: ast.AST, locks: FrozenSet[str],
                 roles: FrozenSet[str], qual: str, prestart: bool,
                 const: bool):
        self.kind = kind
        self.node = node
        self.line = getattr(node, "lineno", 0)
        self.col = getattr(node, "col_offset", 0)
        self.locks = locks
        self.roles = roles
        self.qual = qual
        self.prestart = prestart
        self.const = const


class _RaceIndex:
    """Per-project cache shared by RACE016/ATOM017: every ``self.X``
    access attributed to (thread roles, lockset), plus the inherited
    base locks (intersection over all call sites) per function."""

    __slots__ = ("access", "shared", "base", "registries", "lockrule")

    def __init__(self, access, shared, base, registries, lockrule):
        self.access: Dict[Tuple[str, str, str], List[_Access]] = access
        self.shared: Set[Tuple[str, str, str]] = shared
        self.base: Dict[FuncKey, FrozenSet[str]] = base
        self.registries: Dict[str, Dict[str, str]] = registries
        self.lockrule = lockrule


def _base_locks(proj: ProjectContext, lockrule: LockOrderRule,
                registries: Dict[str, Dict[str, str]]
                ) -> Dict[FuncKey, FrozenSet[str]]:
    """Locks a function inherits from EVERY caller: the intersection
    over all resolvable call sites of (locks held at the site ∪ the
    caller's own inherited locks), to optimistic fixpoint.  Thread
    entry points inherit nothing (the spawn hands over no locks)."""
    incoming: Dict[FuncKey, List[Tuple[FuncKey, FrozenSet[str]]]] = {}
    for caller, edges in proj.calls.items():
        cctx = proj.by_relpath.get(caller[0])
        if cctx is None:
            continue
        reg = registries.get(caller[0], {})
        for e in edges:
            held = _locks_at(cctx, reg, e.site, proj, lockrule)
            incoming.setdefault(e.callee, []).append((caller, held))
    entries = set(proj.thread_entries)
    tops: Set[FuncKey] = {k for k in incoming if k not in entries}
    resolved: Dict[FuncKey, FrozenSet[str]] = {}

    def caller_base(c: FuncKey) -> Optional[FrozenSet[str]]:
        if c in tops:
            return None          # still ⊤ — optimistic, skip the edge
        return resolved.get(c, frozenset())

    changed = True
    while changed:
        changed = False
        for callee, srcs in incoming.items():
            if callee in entries:
                continue
            acc: Optional[FrozenSet[str]] = None
            for caller, held in srcs:
                cb = caller_base(caller)
                if cb is None:
                    continue
                h = held | cb
                acc = h if acc is None else (acc & h)
            if acc is None:
                continue
            if callee in tops:
                tops.discard(callee)
                resolved[callee] = acc
                changed = True
            elif resolved.get(callee) != acc:
                resolved[callee] = acc
                changed = True
    # cycles never reached from an entry stay ⊤ — resolve to ∅ (the
    # choice that reports rather than hides)
    return resolved


def _race_index(proj: ProjectContext) -> _RaceIndex:
    cached = getattr(proj, "_zoolint_race_idx", None)
    if cached is not None:
        return cached
    lockrule = LockOrderRule()
    registries = {ctx.relpath: lockrule._lock_registry(ctx)
                  for ctx in proj.contexts}
    base = _base_locks(proj, lockrule, registries)
    # class-method names per (relpath, class qualname): attribute
    # accesses naming a METHOD are calls, not shared data
    methods_of: Dict[Tuple[str, str], Set[str]] = {}
    for rel, qual in proj.functions:
        head, _, tail = qual.rpartition(".")
        if head:
            methods_of.setdefault((rel, head), set()).add(tail)
    access: Dict[Tuple[str, str, str], List[_Access]] = {}
    for ctx in proj.contexts:
        if "self." not in ctx.source:
            continue
        reg = registries[ctx.relpath]
        sync = _sync_attrs(ctx)
        for fn in ctx.functions:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            qual = ctx.qualname_of(fn)
            if not qual:
                continue
            cls = ctx.enclosing_class(fn)
            if cls is None:
                continue
            clsq = ctx.class_qualname(cls)
            fa = _fn_accesses(fn)
            if not fa:
                continue
            key = (ctx.relpath, qual)
            roles = proj.thread_roles.get(key, frozenset({"main"}))
            basel = base.get(key, frozenset())
            cutoff = _prestart_cutoff(ctx, fn, qual)
            for (attr, line), (kind, node, const) in sorted(
                    fa.items()):
                if attr.startswith("__") and attr.endswith("__"):
                    continue
                if attr in methods_of.get((ctx.relpath, clsq), ()):
                    continue
                if attr in sync.get(clsq, ()):
                    continue
                if f"{ctx.relpath}::{clsq}.{attr}" in reg or \
                        "lock" in attr.lower():
                    continue
                prestart = cutoff is not None and \
                    (cutoff < 0 or line < cutoff)
                locks = basel | _locks_at(ctx, reg, node, proj,
                                          lockrule)
                access.setdefault(
                    (ctx.relpath, clsq, attr), []).append(
                    _Access(kind, node, locks, roles, qual,
                            prestart, const))
    shared: Set[Tuple[str, str, str]] = set()
    for k, accs in access.items():
        all_roles: Set[str] = set()
        for a in accs:
            all_roles |= a.roles
        if len(all_roles) >= 2 or all_roles & _MULTI_ROLES:
            shared.add(k)
    idx = _RaceIndex(access, shared, base, registries, lockrule)
    proj._zoolint_race_idx = idx
    return idx


# ================================================================ RACE016


@register_project_rule
class CrossThreadAttrRaceRule:
    """Instance attributes shared across thread roles with disjoint
    locksets.

    Why: the races that actually bit this repo (the PR 12
    ``_backlog_seen`` gauge stomping, supervisor replica-map
    mutation) live in ``self.*`` state shared between the serving
    plane's threads — invisible to RACE005 (module globals) and
    LOCK010 (lock ORDER, not coverage).  The thread-role inference
    pass attributes every access site to the roles that may execute
    it; two sites conflict when their role union spans ≥ 2 roles (or
    one multi-instance pool role), their locksets share no lock, and
    at least one side mutates in place / read-modify-writes (or both
    plain-write non-constants).  Plain write-on-one-role /
    read-on-another stays exempt: that is the sanctioned atomic-swap
    / monotonic-flag publication idiom (GIL-atomic rebind), and
    queue/Event/deque-typed attributes carry their own contract.
    Pre-``start()`` initialization is exempt — nothing else can hold
    the instance yet.
    """

    rule_id = "RACE016"
    severity = "error"
    doc = ("instance attribute shared across thread roles with "
           "disjoint locksets — a cross-thread data race on self.* "
           "state (CPU-silent, device-fatal)")

    def check_project(self, proj: ProjectContext) -> List[Finding]:
        idx = _race_index(proj)
        findings: List[Finding] = []
        for key in sorted(idx.access):
            rel, clsq, attr = key
            accs = [a for a in idx.access[key] if not a.prestart]
            if len(accs) < 1:
                continue
            accs.sort(key=lambda a: (a.line, -_STRENGTH[a.kind],
                                     a.col))
            pair = self._find_conflict(accs)
            if pair is None:
                continue
            a, b = pair
            ctx = proj.by_relpath[rel]
            findings.append(self._emit(ctx, clsq, attr, a, b))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    # ----------------------------------------------------------- conflict
    @staticmethod
    def _dangerous(a: "_Access", b: "_Access") -> bool:
        if a.locks & b.locks:
            return False
        union = a.roles | b.roles
        if len(union) < 2 and not (union & _MULTI_ROLES):
            return False
        kinds = {a.kind, b.kind}
        if RMW in kinds or MUT in kinds:
            return True
        if kinds == {WRITE}:
            # both sides plain-assign: racy unless it is the
            # constant-flag idiom (every write stores a constant —
            # last-writer-wins on an immutable is benign)
            return not (a.const and b.const)
        return False              # write×read / read×read: exempt

    def _find_conflict(self, accs: List["_Access"]
                       ) -> Optional[Tuple["_Access", "_Access"]]:
        for i, a in enumerate(accs):
            # one site, many threads: an unlocked in-place mutation /
            # RMW executed on ≥ 2 roles races with itself
            if _STRENGTH[a.kind] >= _STRENGTH[RMW] and \
                    not a.locks and (len(a.roles) >= 2
                                     or a.roles & _MULTI_ROLES):
                return (a, a)
            for b in accs[i + 1:]:
                if self._dangerous(a, b):
                    return (a, b)
        return None

    # --------------------------------------------------------------- emit
    def _emit(self, ctx: ModuleContext, clsq: str, attr: str,
              a: "_Access", b: "_Access") -> Finding:
        ra = ",".join(sorted(a.roles))
        if a is b:
            msg = (f"'{clsq}.{attr}' is {_KIND_DESC[a.kind]} with no "
                   f"lock held, and this code runs on roles [{ra}] "
                   f"concurrently — a cross-thread data race on "
                   f"shared instance state. Guard it with one lock "
                   f"on every access, or hand values off through "
                   f"queue.Queue (runtime twin: zoo-racecheck)")
        else:
            rb = ",".join(sorted(b.roles))
            la = f"held {{{', '.join(_short(x) for x in sorted(a.locks))}}}" \
                if a.locks else "no lock held"
            lb = f"held {{{', '.join(_short(x) for x in sorted(b.locks))}}}" \
                if b.locks else "no lock held"
            msg = (f"'{clsq}.{attr}' is {_KIND_DESC[a.kind]} on "
                   f"role(s) [{ra}] at line {a.line} ({la}) and "
                   f"{_KIND_DESC[b.kind]} on role(s) [{rb}] at line "
                   f"{b.line} ({lb}) — the locksets share no lock, "
                   f"so the accesses can interleave mid-update. "
                   f"Guard both sides with the same lock, or hand "
                   f"the value off through queue.Queue (runtime "
                   f"twin: zoo-racecheck)")
        return Finding(
            rule=self.rule_id, severity=self.severity,
            path=ctx.relpath, line=a.line, col=a.col, message=msg,
            symbol=f"{clsq}.{attr}",
            snippet=ctx.line_text(a.line).strip())


# ================================================================ ATOM017


@register_project_rule
class CheckThenActRule:
    """Check-then-act on shared instance state under inconsistent
    locks.

    Why: the PR 12 registry-gauge stomping was exactly this shape —
    ``if key not in self._backlog_seen: … self._backlog_seen[key] =
    gauge`` where the guard and the store did not hold the same lock,
    so two samplers both passed the check and the second stomped the
    first's gauge.  Flow-sensitive over the PR 14 CFG: a guard fact
    (``if``/``while`` test reading ``self.X``, tagged with the locks
    held at the test) flows forward along both branches; a later
    write/mutation of ``X`` reached by a live guard fires when the
    two locksets share no lock (a re-check under the write's lock
    kills the stale outer guard — sanctioned double-checked locking
    stays clean).  With NO lock on either side it fires only when
    thread-role inference proves ``X`` is shared across roles.
    """

    rule_id = "ATOM017"
    severity = "error"
    doc = ("check-then-act on shared instance state under "
           "inconsistent locks (guard and write hold different "
           "locksets — the registry-gauge-stomping class)")

    def check_project(self, proj: ProjectContext) -> List[Finding]:
        idx = _race_index(proj)
        findings: List[Finding] = []
        for ctx in proj.contexts:
            if "self." not in ctx.source:
                continue
            reg = idx.registries[ctx.relpath]
            for fn in ctx.functions:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                cls = ctx.enclosing_class(fn)
                if cls is None:
                    continue
                findings.extend(self._check_method(
                    proj, idx, ctx, reg, fn,
                    ctx.class_qualname(cls)))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    # ------------------------------------------------------------ per-fn
    @staticmethod
    def _acts_in(stmt: Optional[ast.AST]) -> List[Tuple[str, ast.AST]]:
        """(attr, site) for every write/RMW/in-place mutation of a
        ``self`` attribute in this one statement."""
        if stmt is None:
            return []
        out: List[Tuple[str, ast.AST]] = []
        for node in _walk_evaluated([stmt]):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for _kind, attr in _store_accesses(t):
                        out.append((attr, t))
            elif isinstance(node, ast.AugAssign):
                base = _self_base(node.target)
                if base is not None:
                    out.append((base, node.target))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in _MUTATING_METHODS:
                    base = _self_base(f.value)
                    if base is not None:
                        out.append((base, node))
        return out

    @staticmethod
    def _guard_attrs(test: ast.AST, candidates: Set[str]) -> Set[str]:
        return {sub.attr for sub in ast.walk(test)
                if isinstance(sub, ast.Attribute)
                and isinstance(getattr(sub, "ctx", None), ast.Load)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in candidates}

    def _check_method(self, proj: ProjectContext, idx: _RaceIndex,
                      ctx: ModuleContext, reg: Dict[str, str],
                      fn: ast.AST, clsq: str) -> List[Finding]:
        qual = ctx.qualname_of(fn)
        if not qual:
            return []
        tail = qual.rsplit(".", 1)[-1]
        if tail in _INIT_METHODS:
            return []             # construction is single-threaded
        roots = fn.body if isinstance(fn.body, list) else [fn.body]
        acted: Set[str] = set()
        for stmt in roots:
            for attr, _site in self._acts_in(stmt):
                acted.add(attr)
        acted = {a for a in acted
                 if not (a.startswith("__") and a.endswith("__"))
                 and "lock" not in a.lower()}
        if not acted:
            return []
        # any guard on an acted attr?  cheap structural pre-filter
        # before paying for a CFG
        has_guard = any(
            isinstance(n, (ast.If, ast.While))
            and self._guard_attrs(n.test, acted)
            for n in _walk_evaluated(roots))
        if not has_guard:
            return []
        basel = idx.base.get((ctx.relpath, qual), frozenset())
        cfg = _cfg_for(ctx, fn)
        guard_map: Dict[int, List[Tuple[str, Tuple[int, FrozenSet[str]]]]] = {}
        for node in cfg.nodes:
            if node.kind not in ("if", "while") or node.stmt is None:
                continue
            attrs = self._guard_attrs(node.stmt.test, acted)
            if not attrs:
                continue
            glocks = basel | _locks_at(ctx, reg, node.stmt, proj,
                                       idx.lockrule)
            guard_map[id(node.stmt)] = [
                (a, (node.stmt.lineno, glocks)) for a in sorted(attrs)]
        if not guard_map:
            return []
        # acts are keyed to SIMPLE-statement nodes only: a compound
        # header (if/with/for) lexically *contains* its body's writes,
        # but they do not execute at the header — killing/checking
        # there would judge the write against the state BEFORE the
        # body's own locks/re-checks ran
        acts_by_stmt: Dict[int, List[Tuple[str, ast.AST]]] = {}
        for node in cfg.nodes:
            if node.kind == "stmt" and node.stmt is not None:
                acts = [(a, s) for a, s in self._acts_in(node.stmt)
                        if a in acted]
                if acts:
                    acts_by_stmt[id(node.stmt)] = acts
        # a plain READ of self.X between the guard and the act
        # REFRESHES a live token (never creates one): the locked
        # re-read idiom ``exe = self._compiled.get(sig)`` under the
        # write's lock re-bases the decision on fresh data, so the act
        # is judged against the NEAREST observation, not a stale guard
        reads_by_stmt: Dict[int, List[Tuple[str, Tuple[int,
                                                       FrozenSet[str]]]]] = {}
        for node in cfg.nodes:
            if node.kind != "stmt" or node.stmt is None or \
                    id(node.stmt) in acts_by_stmt:
                continue
            attrs = {sub.attr for sub in ast.walk(node.stmt)
                     if isinstance(sub, ast.Attribute)
                     and isinstance(getattr(sub, "ctx", None), ast.Load)
                     and isinstance(sub.value, ast.Name)
                     and sub.value.id == "self" and sub.attr in acted}
            if attrs:
                rlocks = basel | _locks_at(ctx, reg, node.stmt, proj,
                                           idx.lockrule)
                reads_by_stmt[id(node.stmt)] = [
                    (a, (node.stmt.lineno, rlocks))
                    for a in sorted(attrs)]

        def transfer(node: CFGNode, state: State
                     ) -> Dict[Optional[str], State]:
            out = dict(state)
            for attr, _site in acts_by_stmt.get(
                    id(node.stmt) if node.stmt is not None else -1,
                    ()):
                out.pop(f"g:{attr}", None)
            for attr, token in reads_by_stmt.get(
                    id(node.stmt) if node.stmt is not None else -1,
                    ()):
                if f"g:{attr}" in out:     # refresh only, never create
                    out[f"g:{attr}"] = frozenset({token})
            per: Dict[Optional[str], State] = {None: out, EXC: out}
            guards = guard_map.get(
                id(node.stmt) if node.stmt is not None else -1)
            if guards and node.kind in ("if", "while"):
                gout = dict(out)
                for attr, token in guards:
                    # REPLACE, don't union: a re-check supersedes any
                    # stale outer guard (double-checked locking)
                    gout[f"g:{attr}"] = frozenset({token})
                per[TRUE] = gout
                per[FALSE] = gout
            return per

        in_states = run_forward(cfg, {}, transfer)
        findings: List[Finding] = []
        reported: Set[str] = set()
        for node in cfg.nodes:
            if node.stmt is None or \
                    id(node.stmt) not in acts_by_stmt:
                continue
            state = in_states.get(node.idx)
            if not state:
                continue
            for attr, site in acts_by_stmt[id(node.stmt)]:
                if attr in reported:
                    continue
                tokens = state.get(f"g:{attr}")
                if not tokens:
                    continue
                wlocks = basel | _locks_at(ctx, reg, site, proj,
                                           idx.lockrule)
                for gline, glocks in sorted(tokens):
                    if glocks & wlocks:
                        continue
                    if not glocks and not wlocks and (
                            ctx.relpath, clsq, attr) not in idx.shared:
                        continue
                    reported.add(attr)
                    findings.append(self._emit(
                        ctx, clsq, attr, site, gline, glocks, wlocks))
                    break
        return findings

    def _emit(self, ctx: ModuleContext, clsq: str, attr: str,
              site: ast.AST, gline: int, glocks: FrozenSet[str],
              wlocks: FrozenSet[str]) -> Finding:
        gdesc = (f"under {{{', '.join(_short(x) for x in sorted(glocks))}}}"
                 if glocks else "with no lock")
        wdesc = (f"under {{{', '.join(_short(x) for x in sorted(wlocks))}}}"
                 if wlocks else "with no lock")
        msg = (f"check-then-act on '{clsq}.{attr}': the guard at "
               f"line {gline} reads it {gdesc} but this write runs "
               f"{wdesc} — two threads can both pass the check and "
               f"the second stomps the first (the registry-gauge "
               f"class). Hold ONE lock across both the test and the "
               f"update, or re-check under the write's lock")
        return Finding(
            rule=self.rule_id, severity=self.severity,
            path=ctx.relpath, line=getattr(site, "lineno", 0),
            col=getattr(site, "col_offset", 0), message=msg,
            symbol=f"{clsq}.{attr}",
            snippet=ctx.line_text(getattr(site, "lineno", 0)).strip())


# =============================================================== PUBLISH018


#: callback-registration attrs that hand the argument to code running
#: on another thread; ``atexit``/``signal`` register same-thread
#: deferred hooks and are NOT publications for this rule
_REGISTER_ATTRS = ("register", "add_done_callback", "subscribe")


@register_rule
class UnsafePublicationRule(Rule):
    """Object published to another thread while still being mutated.

    Why: the constructing thread hands the object over (a ``Thread``
    target or args, ``queue.put``, a registry/callback registration)
    and KEEPS initializing it — the consumer thread can observe the
    half-built object (the flight-recorder ``replica.spawn`` ordering
    incident: the watch loop read a replica record before its pid
    field landed).  Publication points: ``t.start()`` on a
    constructed Thread (including the chained
    ``Thread(...).start()``), ``queue.put``/``put_nowait``,
    ``executor.submit`` extra args, and callback registration.  For
    ``target=self._run`` the published object is ``self`` — only
    mutations of attributes the target method actually touches are
    flagged (role-steady-state writes belong to RACE016; this rule is
    about ORDERING).  A mutation under any held lock is exempt.
    """

    rule_id = "PUBLISH018"
    severity = "warning"
    doc = ("unsafe publication: object handed to another thread "
           "(Thread target/args, queue.put, callback registration) "
           "while the constructing method keeps mutating it")

    _SPAWN_CTORS = ("threading.Thread", "Thread", "threading.Timer",
                    "Timer")

    def check_module(self, ctx: ModuleContext) -> None:
        src = ctx.source
        if not (".start(" in src or ".put(" in src
                or ".submit(" in src or ".register(" in src
                or "add_done_callback" in src):
            return
        self._registry = LockOrderRule()._lock_registry(ctx)
        for fn in _functions(ctx):
            self._check_function(ctx, fn)

    # ----------------------------------------------------------- capture
    def _target_attr_touches(self, ctx: ModuleContext, fn: ast.AST,
                             target: Optional[ast.AST]
                             ) -> Optional[Set[str]]:
        """When the spawn target is ``self.m``, the set of ``self``
        attributes the method ``m`` touches (reads or writes) — the
        attrs whose post-publication mutation the consumer can
        observe.  None when the target is not a resolvable
        same-class method (→ don't track ``self``)."""
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            return None
        cls = ctx.enclosing_class(fn)
        if cls is None:
            return None
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    stmt.name == target.attr:
                return {sub.attr for sub in ast.walk(stmt)
                        if isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"}
        return None

    def _thread_captures(self, ctx: ModuleContext, fn: ast.AST,
                         call: ast.Call
                         ) -> Tuple[Set[str], Optional[Set[str]]]:
        """(published local names, self-attr filter) for a Thread/
        Timer construction: every local Name referenced in target=/
        args=/kwargs= is handed to the new thread; ``target=self.m``
        publishes ``self`` filtered to the attrs ``m`` touches."""
        names: Set[str] = set()
        target: Optional[ast.AST] = None
        self_filter: Optional[Set[str]] = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            if kw.arg in ("target", "args", "kwargs"):
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Name) and \
                            sub.id not in ("self", "cls"):
                        names.add(sub.id)
        touches = self._target_attr_touches(ctx, fn, target)
        if touches is not None:
            self_filter = touches
        # a local-function target: its free-variable reads are
        # captured names too (closure handoff)
        if isinstance(target, ast.Name):
            local = ctx._local_function_named(call, target.id)
            if local is not None:
                for sub in ast.walk(local):
                    if isinstance(sub, ast.Name) and \
                            isinstance(getattr(sub, "ctx", None),
                                       ast.Load):
                        names.add(sub.id)
        return names, self_filter

    # ------------------------------------------------------------- check
    def _check_function(self, ctx: ModuleContext, fn: ast.AST) -> None:
        roots = fn.body if isinstance(fn.body, list) else [fn.body]
        #: constructed-but-unstarted threads: dotted target name of
        #: the binding -> (captured names, self filter)
        pending: Dict[str, Tuple[Set[str], Optional[Set[str]]]] = {}
        #: published name -> (publication line, self filter or None)
        published: Dict[str, Tuple[int, Optional[Set[str]]]] = {}

        def publish(name: str, line: int,
                    filt: Optional[Set[str]] = None) -> None:
            cur = published.get(name)
            if cur is None:
                published[name] = (line, filt)
            else:
                merged = None if (cur[1] is None or filt is None) \
                    else (cur[1] | filt)
                published[name] = (min(cur[0], line), merged)

        # _walk_evaluated yields in stack (reverse) order; this pass
        # is a state machine (construct -> pending -> .start() ->
        # published), so it must see the statements in SOURCE order
        # or every non-chained publication is missed
        ordered = sorted(
            _walk_evaluated(roots),
            key=lambda n: (getattr(n, "lineno", 0),
                           getattr(n, "col_offset", 0)))
        for node in ordered:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                resolved = ctx.resolve(node.value.func) or ""
                if resolved in self._SPAWN_CTORS:
                    caps = self._thread_captures(ctx, fn, node.value)
                    for t in node.targets:
                        d = _dotted(t)
                        if d:
                            pending[d] = caps
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr == "start" and not node.args:
                # chained Thread(...).start() publishes immediately
                if isinstance(f.value, ast.Call) and \
                        (ctx.resolve(f.value.func) or "") in \
                        self._SPAWN_CTORS:
                    caps, filt = self._thread_captures(
                        ctx, fn, f.value)
                    for n in caps:
                        publish(n, node.lineno)
                    if filt is not None:
                        publish("self", node.lineno, filt)
                    continue
                d = _dotted(f.value)
                if d in pending:
                    caps, filt = pending[d]
                    for n in caps:
                        publish(n, node.lineno)
                    if filt is not None:
                        publish("self", node.lineno, filt)
            elif f.attr in ("put", "put_nowait"):
                for a in node.args:
                    if isinstance(a, ast.Name):
                        publish(a.id, node.lineno)
            elif f.attr == "submit":
                recv = (_dotted(f.value) or "").lower()
                if "pool" in recv or "executor" in recv:
                    for a in node.args[1:]:
                        if isinstance(a, ast.Name):
                            publish(a.id, node.lineno)
            elif f.attr in _REGISTER_ATTRS:
                resolved = ctx.resolve(f) or ""
                if resolved.startswith(("atexit.", "signal.")):
                    continue
                for a in node.args:
                    if isinstance(a, ast.Name):
                        publish(a.id, node.lineno)
                    elif isinstance(a, ast.Attribute) and \
                            isinstance(a.value, ast.Name) and \
                            a.value.id == "self":
                        touches = self._target_attr_touches(
                            ctx, fn, a)
                        if touches is not None:
                            publish("self", node.lineno, touches)
        if not published:
            return
        self._flag_mutations(ctx, fn, roots, published)

    def _flag_mutations(self, ctx: ModuleContext, fn: ast.AST,
                        roots: Sequence[ast.AST],
                        published: Dict[str, Tuple[int,
                                                   Optional[Set[str]]]]
                        ) -> None:
        reported: Set[Tuple[str, str]] = set()
        for node in _walk_evaluated(roots):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            else:
                continue
            for t in targets:
                base = self._mut_base(t)
                if base is None:
                    continue
                name, attr = base
                pub = published.get(name)
                if pub is None or node.lineno <= pub[0]:
                    continue
                if name == "self":
                    filt = pub[1]
                    if filt is None or attr not in filt:
                        continue
                if (name, attr) in reported:
                    continue
                if _locks_at(ctx, self._registry, node, None,
                             LockOrderRule()):
                    continue
                reported.add((name, attr))
                what = f"'{name}.{attr}'" if name != "self" \
                    else f"'self.{attr}'"
                self.report(
                    node,
                    f"{what} is mutated after '{name}' was handed "
                    f"to another thread at line {pub[0]} — the "
                    f"consumer can observe the half-initialized "
                    f"object (unsafe publication). Finish "
                    f"initializing BEFORE publishing, or guard "
                    f"both sides with a lock (runtime twin: the "
                    f"flight-recorder replica.spawn ordering)",
                    line=node.lineno)

    @staticmethod
    def _mut_base(t: ast.AST) -> Optional[Tuple[str, str]]:
        """(base local name, attr) for a mutation target rooted at a
        bare Name: ``obj.x = …``, ``obj.x[k] = …``, ``obj[k] = …``."""
        attr = ""
        cur = t
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            if isinstance(cur, ast.Attribute):
                attr = cur.attr
            cur = cur.value
        if isinstance(cur, ast.Name) and not isinstance(
                t, ast.Name):
            return (cur.id, attr or "[]")
        return None


# ================================================================ WRITE019


#: path-variable spellings that mean "a directory some OTHER process
#: or thread reads while we run" (the run-dir contract)
_RUNDIR_TOKENS = ("run_dir", "rundir", "run_path", "run_root",
                  "out_dir", "output_dir", "shared_dir", "out_path",
                  "output_path", "report_path", "report_out")


@register_rule
class NonAtomicSharedWriteRule(Rule):
    """Non-atomic ``open(path, "w")`` to a run-dir-shared path.

    Why: every run-dir artifact has concurrent readers by contract —
    obs_report tails progress files, zoo-doctor reads journals from
    live runs, batch peers poll each other's markers.  A plain
    ``open(.., "w")`` truncates THEN writes: a reader in that window
    sees an empty or torn file (the PR 9 loader-duplication debt —
    every site hand-rolled its own write-then-rename).  Route new
    writes through ``common.fsutil.atomic_write_text`` /
    ``atomic_write_bytes`` (write tmp sibling, ``os.replace``).
    Temp-file spellings (a name or literal containing ``tmp``) are
    the sanctioned pattern's first half and stay exempt.
    """

    rule_id = "WRITE019"
    severity = "warning"
    doc = ("non-atomic open(path, 'w') to a run-dir-shared path — "
           "concurrent readers see a torn file; use "
           "common.fsutil.atomic_write_text/_bytes")

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        path_expr = self._open_write_path(node)
        if path_expr is None:
            return
        texts = self._path_texts(path_expr)
        low = [t.lower() for t in texts]
        if any("tmp" in t for t in low):
            return
        if not any(tok in t for t in low for tok in _RUNDIR_TOKENS):
            return
        self.report(
            node,
            "non-atomic write to a run-dir-shared path: open(.., "
            "'w') truncates before it writes, so a concurrent "
            "reader (obs_report, zoo-doctor, a peer worker) sees an "
            "empty or torn file. Use common.fsutil."
            "atomic_write_text/atomic_write_bytes (tmp sibling + "
            "os.replace)")

    @staticmethod
    def _open_write_path(node: ast.Call) -> Optional[ast.AST]:
        """The path expression when this call is ``open(path, 'w'/
        'wb')`` or ``path.open('w'/'wb')``; None otherwise."""
        f = node.func
        mode: Optional[str] = None
        args = node.args
        if isinstance(f, ast.Name) and f.id == "open":
            if len(args) >= 2 and isinstance(args[1], ast.Constant):
                mode = args[1].value
            path = args[0] if args else None
        elif isinstance(f, ast.Attribute) and f.attr == "open":
            if args and isinstance(args[0], ast.Constant):
                mode = args[0].value
            path = f.value
        else:
            return None
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if mode in ("w", "wb") and path is not None:
            return path
        return None

    @staticmethod
    def _path_texts(expr: ast.AST) -> List[str]:
        out: List[str] = []
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name):
                out.append(sub.id)
            elif isinstance(sub, ast.Attribute):
                out.append(sub.attr)
            elif isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                out.append(sub.value)
        return out
