"""SARIF 2.1.0 export for zoolint findings.

SARIF (Static Analysis Results Interchange Format) is what code-review
surfaces ingest — GitHub code scanning, VS Code SARIF viewers, Jenkins
warnings-ng — so the CI gate's findings can annotate the diff instead
of living in a console log.  One runs[] entry; the rule catalog is
emitted from the live registry (``cli.rule_catalog``) so the metadata
can never drift from the rules actually run.  Stdlib-only, like the
rest of the analyzer.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from analytics_zoo_tpu.analysis.core import Finding

SARIF_VERSION = "2.1.0"
_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")

#: zoolint severity -> SARIF level
_LEVELS = {"error": "error", "warning": "warning"}


def sarif_report(findings: Sequence[Finding],
                 errors: Sequence[str] = ()) -> Dict:
    """The findings (post-baseline/diff — what the run actually FAILS
    on) as one SARIF 2.1.0 document.  Unparseable-file errors ride
    along as tool-level notifications: a file the linter could not
    read is a result consumers must see too."""
    from analytics_zoo_tpu.analysis.cli import rule_catalog
    rules: List[Dict] = []
    seen = set()
    for rid, severity, doc in rule_catalog():
        if rid in seen:
            continue
        seen.add(rid)
        rules.append({
            "id": rid,
            "shortDescription": {"text": doc},
            "defaultConfiguration": {
                "level": _LEVELS.get(severity, "warning")},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "partialFingerprints": {"zoolintKey/v1": f.key()},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
                "logicalLocations": ([{"name": f.symbol}]
                                     if f.symbol else []),
            }],
        })
    notifications = [{"level": "error", "message": {"text": e}}
                     for e in errors]
    # no informationUri: SARIF 2.1.0 requires an ABSOLUTE URI for it
    # and this repo has no canonical public URL — strict ingesters
    # (github code scanning) reject relative values, and the
    # property is optional
    run: Dict = {
        "tool": {"driver": {
            "name": "zoolint",
            "rules": rules,
        }},
        "results": results,
    }
    if notifications:
        run["invocations"] = [{
            "executionSuccessful": False,
            "toolExecutionNotifications": notifications,
        }]
    return {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }
