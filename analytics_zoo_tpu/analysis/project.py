"""zoolint's interprocedural layer: module graph + call graph.

PR 5's rules were intraprocedural — a ``print`` inside a helper
*called from* a jitted step, or a PRNG key handed to a consuming
helper, was invisible.  This module links every analyzed file into a
:class:`ProjectContext` and propagates the facts the per-module rules
consume:

- **traced reachability**: a function called (transitively) from a
  jit/trace-compiled function is itself traced — JIT001/COMPILE003
  then see through helper calls;
- **hot-loop reachability**: a function called from inside a
  train/step/predict loop is loop-resident wholesale — SYNC002/MEM009
  then flag the per-iteration device pull it hides;
- **PRNG consumer summaries**: which parameters of each function end
  up consumed by a ``jax.random`` primitive, so a call site passing
  the same key to two consuming helpers is an RNG006 finding;
- **cross-module jitted callables** and the **mesh axis universe**
  (every ``*_AXIS`` constant / ``Mesh(...)`` axis literal in the
  project) for COMPILE003/MEM009/SHARD007;
- **lock summaries** (which locks each function acquires, which
  functions block) consumed by LOCK010's project-wide deadlock pass.

Resolution is deliberately conservative — precision over recall, the
same contract the PR 5 rules keep.  A call resolves only when it is a
direct name (local def, name-bound lambda, imported function) or a
single-level ``self.method()`` / class instantiation; everything else
(duck-typed objects, ``model.apply``) stays unresolved and propagates
nothing.

All results are exported as a **picklable per-module fact bundle**
(:meth:`ProjectContext.compute_facts` →
``ModuleContext.apply_facts``), the only channel into the per-module
rule runs — which is what lets ``zoolint --jobs N`` fan those runs
out over a process pool without re-doing (or disagreeing about) the
whole-program analysis.

Stdlib-only; never imports jax (the ``scripts/zoolint`` contract).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Finding, ModuleContext, _dotted, donated_positions)

FuncKey = Tuple[str, str]          # (relpath, qualname)

#: fallback axis names when the project defines none (matches
#: parallel/mesh.py's canonical ALL_AXES — kept literal so the
#: analyzer never imports the package it lints)
CANONICAL_AXES = ("data", "fsdp", "model", "seq", "pipe", "expert")

#: parameter names that look like optimizer state — the signature of
#: a *train* step (vs eval/predict)
STATE_PARAMS = ("opt_state", "optimizer_state", "opt_states")


def _jit_kw_spec(kws) -> Dict:
    """Picklable spec of a jit wrapper's keywords for the fact
    bundle.  ``donate_pos`` preserves the LITERAL donate_argnums
    positions so MEM009's coverage check survives the module
    boundary (None = declared but unmappable — assume covered)."""
    spec: Dict = {
        "static": any(k.arg in ("static_argnums", "static_argnames")
                      for k in kws),
        "donate": any(k.arg in ("donate_argnums", "donate_argnames")
                      for k in kws),
    }
    if spec["donate"]:
        pos = donated_positions(kws)
        spec["donate_pos"] = None if pos is None else sorted(pos)
    return spec


class CallEdge:
    __slots__ = ("site", "callee", "in_callback")

    def __init__(self, site: ast.Call, callee: FuncKey,
                 in_callback: bool):
        self.site = site
        self.callee = callee
        self.in_callback = in_callback


class ProjectContext:
    """The linked view over every analyzed module."""

    def __init__(self, contexts: Sequence[ModuleContext]):
        self.contexts = list(contexts)
        self.by_relpath: Dict[str, ModuleContext] = {
            c.relpath: c for c in self.contexts}
        self.by_module: Dict[str, ModuleContext] = {
            c.module_name: c for c in self.contexts}
        #: (relpath, qualname) -> function nodes (lambda quals repeat)
        self.functions: Dict[FuncKey, List[ast.AST]] = {}
        self._qual_of: Dict[int, str] = {}      # id(fn) -> qualname
        #: caller FuncKey -> resolved outgoing call edges
        self.calls: Dict[FuncKey, List[CallEdge]] = {}
        #: jit-root train-step functions (thread opt-state), for the
        #: --explain-comms / --explain-hbm reports
        self.train_steps: List[Dict] = []
        self.axis_names: Set[str] = set()
        self.axis_constants: Dict[str, str] = {}
        #: per-ctx trace-wrapper call sites found during the scan
        self._wrapper_calls: Dict[str, List[Tuple[ast.Call, str]]] = {}
        self._index_functions()
        self._scan_modules()
        self._marks_traced: Dict[FuncKey, Tuple[str, str]] = {}
        self._marks_hot: Dict[FuncKey, str] = {}
        #: relpath -> {name: {"static","donate"}} for jit targets only
        #: the project resolution could see (ride the fact bundle)
        self._seed_jitted: Dict[str, Dict[str, Dict]] = {}
        self._rng_consumed: Dict[FuncKey, Set[str]] = {}
        self._rng_call_facts: Dict[str, Dict[Tuple[int, int],
                                             List[str]]] = {}
        #: thread-role inference (zoolint v4): FuncKey -> role set,
        #: and the spawn-target entry points seeding it
        self.thread_entries: Dict[FuncKey, Set[str]] = {}
        self.thread_roles: Dict[FuncKey, frozenset] = {}
        self._propagate_traced()
        self._propagate_hot_loops()
        self._summarize_rng_consumers()
        self._collect_train_steps()
        self._infer_thread_roles()

    # ------------------------------------------------------------ indexing
    def _index_functions(self) -> None:
        for ctx in self.contexts:
            for fn in ctx.functions:
                qual = ctx._qualnames.get(id(fn), "")
                if not qual:
                    continue
                self._qual_of[id(fn)] = qual
                self.functions.setdefault(
                    (ctx.relpath, qual), []).append(fn)

    def ctx_for(self, key: FuncKey) -> Optional[ModuleContext]:
        return self.by_relpath.get(key[0])

    def node_for(self, key: FuncKey) -> Optional[ast.AST]:
        nodes = self.functions.get(key)
        return nodes[0] if nodes else None

    def func_params(self, key: FuncKey) -> List[str]:
        return self.func_params_of_node(self.node_for(key))

    # ------------------------------------- the one per-module scan
    def _scan_modules(self) -> None:
        """ONE walk per module collecting everything the project
        needs from call sites: the axis universe, the resolved call
        graph, trace-wrapper sites (jit seeds), callback-protected
        regions.  Merged because tree traversal dominates the whole
        pass (the full-repo gate is CI's slowest tier-1 subprocess)."""
        from analytics_zoo_tpu.analysis.rules import ImpureJitRule
        for ctx in self.contexts:
            for stmt in ctx.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                if isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name) and \
                                tgt.id.endswith("_AXIS"):
                            axis = stmt.value.value
                            self.axis_names.add(axis)
                            self.axis_constants[
                                f"{ctx.module_name}.{tgt.id}"] = axis
            callback_sites: List[ast.Call] = []
            edges: List[Tuple[FuncKey, CallEdge]] = []
            wrappers: List[Tuple[ast.Call, str]] = []
            for node in ctx.all_nodes:
                if not isinstance(node, ast.Call):
                    continue
                fname = ctx.resolve(node.func) or ""
                tail = fname.rsplit(".", 1)[-1]
                if tail == "Mesh" and len(node.args) >= 2:
                    self.axis_names.update(
                        self._string_elts(node.args[1]))
                elif tail == "create_mesh":
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Dict):
                            for k in arg.keys:
                                if isinstance(k, ast.Constant) and \
                                        isinstance(k.value, str):
                                    self.axis_names.add(k.value)
                if fname in ImpureJitRule.CALLBACK_HOSTS:
                    callback_sites.append(node)
                if fname in ctx.TRACE_WRAPPERS and node.args:
                    wrappers.append((node, fname))
                caller = ctx.enclosing_function(node)
                if caller is None:
                    continue   # module-level init: runs once, untraced
                caller_qual = self._qual_of.get(id(caller))
                if not caller_qual:
                    continue
                callee = self.resolve_call(ctx, node)
                if callee is None:
                    continue
                edges.append(((ctx.relpath, caller_qual),
                              CallEdge(node, callee, False)))
            # callback-host args run on HOST, not under the trace:
            # flag the edges inside them (rare — walk only their args)
            protected: Set[int] = set()
            for cb in callback_sites:
                for arg in list(cb.args) + \
                        [kw.value for kw in cb.keywords]:
                    for sub in ast.walk(arg):
                        protected.add(id(sub))
            for key, edge in edges:
                if id(edge.site) in protected:
                    edge.in_callback = True
                self.calls.setdefault(key, []).append(edge)
            self._wrapper_calls[ctx.relpath] = wrappers

    @staticmethod
    def _string_elts(node: ast.AST) -> List[str]:
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        return []

    def resolve_call(self, ctx: ModuleContext,
                     call: ast.Call) -> Optional[FuncKey]:
        """Resolve a call site to a (relpath, qualname) when it can be
        done conservatively; None otherwise."""
        return self.resolve_func_expr(ctx, call.func, call)

    def resolve_func_expr(self, ctx: ModuleContext, expr: ast.AST,
                          origin: ast.AST,
                          depth: int = 0) -> Optional[FuncKey]:
        """Resolve an expression DENOTING a callable (the jit arg in
        ``jax.jit(self._step_core)``, a call's ``func``)."""
        if depth > 4:
            return None
        if isinstance(expr, ast.Lambda):
            qual = self._qual_of.get(id(expr))
            return (ctx.relpath, qual) if qual else None
        if isinstance(expr, ast.Call):
            # functools.partial(f, ...) denotes f
            if ctx.resolve(expr.func) in ("functools.partial",
                                          "partial") and expr.args:
                return self.resolve_func_expr(ctx, expr.args[0],
                                              origin, depth + 1)
            return None
        if isinstance(expr, ast.Name):
            target = ctx._local_function_named(origin, expr.id)
            if target is not None:
                qual = self._qual_of.get(id(target))
                return (ctx.relpath, qual) if qual else None
            lam = ctx._local_lambda_named(origin, expr.id)
            if lam is not None:
                qual = self._qual_of.get(id(lam))
                return (ctx.relpath, qual) if qual else None
            bound = self._local_binding_value(ctx, origin, expr.id)
            if bound is not None:
                return self.resolve_func_expr(ctx, bound, origin,
                                              depth + 1)
            dotted = ctx.aliases.get(expr.id)
            if dotted and dotted != expr.id:
                return self._resolve_dotted(dotted)
            return None
        if isinstance(expr, ast.Attribute):
            d = _dotted(expr)
            if d is None:
                return None
            head = d.split(".", 1)[0]
            if head in ("self", "cls"):
                if d.count(".") != 1:
                    return None   # self.a.b — another object's method
                cls = ctx.enclosing_class(origin)
                if cls is None:
                    return None
                qual = f"{ctx.class_qualname(cls)}.{expr.attr}"
                if (ctx.relpath, qual) in self.functions:
                    return (ctx.relpath, qual)
                return None
            resolved = ctx.resolve(expr)
            if resolved:
                return self._resolve_dotted(resolved)
        return None

    @staticmethod
    def _local_binding_value(ctx: ModuleContext, origin: ast.AST,
                             name: str) -> Optional[ast.AST]:
        """The RHS of the deepest in-scope ``name = <expr>`` binding
        (used to chase ``fn = self._step_core; jax.jit(fn)``)."""
        return ctx.scoped_binding_value(
            origin, name, (ast.Attribute, ast.Name))

    def _resolve_dotted(self, dotted: str) -> Optional[FuncKey]:
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mctx = self.by_module.get(".".join(parts[:i]))
            if mctx is None:
                continue
            rest = ".".join(parts[i:])
            if (mctx.relpath, rest) in self.functions:
                return (mctx.relpath, rest)
            init = f"{rest}.__init__"
            if (mctx.relpath, init) in self.functions:
                return (mctx.relpath, init)
            return None
        return None

    # --------------------------------------------- traced propagation
    def _traced_seeds(self) -> Dict[FuncKey, bool]:
        """FuncKey -> compiled? for every function each module already
        discovered as traced, plus jit-wrapper args only the richer
        project resolution can see (``jax.jit(self._step_core)``,
        ``fn = lambda ...; jax.jit(fn)``)."""
        seeds: Dict[FuncKey, bool] = {}
        for ctx in self.contexts:
            for fn in ctx.functions:
                if id(fn) in ctx.traced_functions:
                    qual = self._qual_of.get(id(fn))
                    if qual:
                        key = (ctx.relpath, qual)
                        seeds[key] = seeds.get(key, False) or \
                            id(fn) in ctx.jit_functions
            for node, fname in self._wrapper_calls.get(
                    ctx.relpath, ()):
                key = self.resolve_func_expr(ctx, node.args[0], node)
                if key is None:
                    continue
                compiled = fname in ctx.JIT_WRAPPERS
                seeds[key] = seeds.get(key, False) or compiled
                node_fn = self.node_for(key)
                kctx = self.ctx_for(key)
                if node_fn is not None and kctx is not None:
                    reason = (f"wrapped by {fname} at "
                              f"{ctx.relpath}:{node.lineno}")
                    kctx.force_traced(node_fn, compiled, reason)
                    # ALSO record into the fact bundle: the bundle is
                    # the documented only-channel into per-module rule
                    # runs, so a worker that re-parses (rather than
                    # fork-inherits) must see these marks too
                    prev = self._marks_traced.get(key)
                    if prev is None or (compiled and prev[0] != "jit"):
                        self._marks_traced[key] = (
                            "jit" if compiled else "trace", reason)
                    if key[0] == ctx.relpath and \
                            isinstance(node_fn, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)) \
                            and compiled and "." not in key[1] \
                            and self._jit_rebinds_name(ctx, node,
                                                       key[1]):
                        # only when the jit result is bound BACK onto
                        # the wrapped function's own name (``helper =
                        # jax.jit(helper)``) does calling that name
                        # run compiled code — ``step = jax.jit(
                        # helper)`` leaves direct ``helper(...)``
                        # calls eager, and flagging them as jit call
                        # sites minted false MEM009/COMPILE003 hits
                        ctx.jitted_callables.setdefault(
                            key[1], list(node.keywords))
                        self._seed_jitted.setdefault(
                            ctx.relpath, {}).setdefault(
                                key[1], _jit_kw_spec(node.keywords))
        return seeds

    @staticmethod
    def _jit_rebinds_name(ctx: ModuleContext, call: ast.Call,
                          name: str) -> bool:
        """Is the jit-wrapper ``call`` assigned back onto ``name``
        itself (possibly through chained wrappers like
        ``monitor.wrap(jax.jit(f))``)?"""
        cur = ctx.parent(call)
        while isinstance(cur, ast.Call):
            cur = ctx.parent(cur)
        if not isinstance(cur, ast.Assign):
            return False
        for tgt in cur.targets:
            if isinstance(tgt, ast.Name) and tgt.id == name:
                return True
        return False

    def _propagate_traced(self) -> None:
        seeds = self._traced_seeds()
        state: Dict[FuncKey, bool] = dict(seeds)
        queue = list(seeds.items())
        while queue:
            key, compiled = queue.pop()
            for edge in self.calls.get(key, ()):
                if edge.in_callback:
                    continue   # host side-channel out of the trace
                cur = state.get(edge.callee)
                if cur is None or (compiled and not cur):
                    state[edge.callee] = compiled or bool(cur)
                    reason = (f"called from "
                              f"{'jitted' if compiled else 'traced'} "
                              f"{key[1]} ({key[0]}:"
                              f"{edge.site.lineno})")
                    if edge.callee not in seeds:
                        self._marks_traced[edge.callee] = (
                            "jit" if state[edge.callee] else "trace",
                            reason)
                    queue.append((edge.callee, state[edge.callee]))

    # ------------------------------------------- hot-loop propagation
    def _propagate_hot_loops(self) -> None:
        traced = set(self._marks_traced)
        hot: Dict[FuncKey, str] = {}
        queue: List[FuncKey] = []

        def callee_is_traced(key: FuncKey) -> bool:
            if key in traced:
                return True
            kctx = self.ctx_for(key)
            node = self.node_for(key)
            return bool(kctx and node and
                        id(node) in kctx.traced_functions)

        for (rel, qual), edges in self.calls.items():
            ctx = self.by_relpath[rel]
            if (rel, qual) in traced:
                continue   # traced by propagation, not host
            caller_nodes = self.functions.get((rel, qual), [])
            for edge in edges:
                caller = ctx.enclosing_function(edge.site)
                if caller is None or caller not in caller_nodes:
                    continue
                if not ctx.is_hot_function(caller):
                    continue
                if not ctx.in_loop(edge.site, lexical_only=True):
                    continue
                if self._in_except_handler(ctx, edge.site):
                    continue   # recovery paths run once per
                    # failure, not per steady-state iteration
                if callee_is_traced(edge.callee):
                    continue   # dispatching a jit is the POINT
                if edge.callee not in hot:
                    hot[edge.callee] = (
                        f"called from the loop in hot "
                        f"{qual} ({rel}:{edge.site.lineno})")
                    queue.append(edge.callee)
        # a hot-loop-resident function's ENTIRE body is loop code:
        # every call it makes is per-iteration too
        while queue:
            key = queue.pop()
            kctx = self.ctx_for(key)
            for edge in self.calls.get(key, ()):
                if callee_is_traced(edge.callee):
                    continue
                if kctx is not None and \
                        self._in_except_handler(kctx, edge.site):
                    continue
                if edge.callee not in hot:
                    hot[edge.callee] = (
                        f"reached from a hot loop via {key[1]} "
                        f"({key[0]}:{edge.site.lineno})")
                    queue.append(edge.callee)
        self._marks_hot = hot

    @staticmethod
    def _in_except_handler(ctx: ModuleContext, node: ast.AST) -> bool:
        cur = ctx.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.Lambda)):
            if isinstance(cur, ast.ExceptHandler):
                return True
            cur = ctx.parent(cur)
        return False

    # --------------------------------------------- RNG consumer summaries
    def _direct_key_consumptions(
            self, ctx: ModuleContext,
            fn: ast.AST) -> Set[str]:
        """Parameter names of ``fn`` consumed by a jax.random
        primitive (or rng= kwarg) directly in its body."""
        from analytics_zoo_tpu.analysis.rules import KeyReuseRule
        params = set(self.func_params_of_node(fn))
        if not params:
            return set()
        out: Set[str] = set()
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue   # nested scope: separate timing
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if name and name.startswith("jax.random."):
                prim = name.rsplit(".", 1)[1]
                if prim in KeyReuseRule.DERIVE:
                    continue
                if node.args and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in params:
                    out.add(node.args[0].id)
            else:
                for kw in node.keywords:
                    if kw.arg == "rng" and \
                            isinstance(kw.value, ast.Name) and \
                            kw.value.id in params:
                        out.add(kw.value.id)
        return out

    @staticmethod
    def func_params_of_node(fn: Optional[ast.AST]) -> List[str]:
        args = getattr(fn, "args", None)
        if args is None:
            return []
        return [a.arg for a in
                (args.posonlyargs + args.args + args.kwonlyargs)]

    def _consumed_args_at(self, ctx: ModuleContext, call: ast.Call,
                          callee: FuncKey,
                          consumed: Dict[FuncKey, Set[str]]
                          ) -> List[str]:
        """Names of Name-arguments at ``call`` that land on a
        key-consuming parameter of ``callee``."""
        target_params = consumed.get(callee)
        if not target_params:
            return []
        params = self.func_params(callee)
        offset = 0
        if isinstance(call.func, ast.Attribute):
            head = _dotted(call.func) or ""
            if head.split(".", 1)[0] in ("self", "cls") and params \
                    and params[0] in ("self", "cls"):
                offset = 1
        out: List[str] = []
        for i, arg in enumerate(call.args):
            j = i + offset
            if j < len(params) and params[j] in target_params and \
                    isinstance(arg, ast.Name):
                out.append(arg.id)
        for kw in call.keywords:
            if kw.arg in target_params and \
                    isinstance(kw.value, ast.Name):
                out.append(kw.value.id)
        return out

    def _summarize_rng_consumers(self) -> None:
        consumed: Dict[FuncKey, Set[str]] = {}
        for ctx in self.contexts:
            for fn in ctx.functions:
                qual = self._qual_of.get(id(fn))
                if not qual:
                    continue
                direct = self._direct_key_consumptions(ctx, fn)
                if direct:
                    consumed[(ctx.relpath, qual)] = direct
        # transitive: a param forwarded into a consuming position of a
        # resolvable callee is consumed too
        changed = True
        while changed:
            changed = False
            for key, edges in self.calls.items():
                params = set(self.func_params(key))
                if not params:
                    continue
                for edge in edges:
                    for name in self._consumed_args_at(
                            self.ctx_for(key), edge.site, edge.callee,
                            consumed):
                        if name in params and \
                                name not in consumed.get(key, ()):
                            consumed.setdefault(key, set()).add(name)
                            changed = True
        self._rng_consumed = consumed
        # per-call-site facts for RNG006
        for key, edges in self.calls.items():
            ctx = self.ctx_for(key)
            for edge in edges:
                names = self._consumed_args_at(ctx, edge.site,
                                               edge.callee, consumed)
                if names:
                    self._rng_call_facts.setdefault(
                        key[0], {})[(edge.site.lineno,
                                     edge.site.col_offset)] = names

    # --------------------------------------------------- train-step roots
    def _collect_train_steps(self) -> None:
        """Jit-compiled functions that thread optimizer state — the
        steps the --explain-comms/--explain-hbm reports describe."""
        seen: Set[FuncKey] = set()
        for ctx in self.contexts:
            for fn in ctx.functions:
                qual = self._qual_of.get(id(fn), "")
                key = (ctx.relpath, qual)
                if id(fn) not in ctx.jit_functions and \
                        self._marks_traced.get(key, ("",))[0] != "jit":
                    continue
                if key in seen:
                    continue
                params = self.func_params_of_node(fn)
                if not any(p in STATE_PARAMS for p in params):
                    continue
                seen.add(key)
                self.train_steps.append({
                    "path": ctx.relpath,
                    "symbol": qual or "<lambda>",
                    "line": getattr(fn, "lineno", 1),
                    "params": params,
                })
        self.train_steps.sort(key=lambda d: (d["path"], d["line"]))

    # ------------------------------------------------- thread roles (v4)
    #: callables whose target/callback runs on ANOTHER thread (or in a
    #: teardown context concurrent with daemon threads)
    _SPAWN_CTORS = {"threading.Thread", "Thread", "threading.Timer",
                    "Timer", "_thread.start_new_thread"}

    def _infer_thread_roles(self) -> None:
        """Discover thread entry points (``Thread(target=...)``,
        executor ``submit``, ``atexit``/``signal`` hooks) and compute,
        to fixpoint through the call graph, which functions run on
        which ROLES — so every ``self.attr`` access site can be
        attributed to the set of threads that may execute it.

        Role naming: the spawn's literal ``name=`` kwarg when present
        (its last ``-``-separated token: ``"zoo-serving-batcher"`` →
        ``batcher``), else the entry function's qualname.  ``main`` is
        the implicit role of everything reachable outside any spawn
        target.  Propagation never flows INTO an entry function: its
        roles come from its spawn sites only (a ``run()`` used both
        foreground and as a thread target keeps the thread role — the
        conservative choice for race detection)."""
        entries: Dict[FuncKey, Set[str]] = {}
        for ctx in self.contexts:
            for node in ctx.all_nodes:
                if not isinstance(node, ast.Call):
                    continue
                target, hint = self._spawn_target(ctx, node)
                if target is None:
                    continue
                key = self.resolve_func_expr(ctx, target, node)
                if key is None:
                    continue
                role = hint or key[1].rsplit(".", 1)[-1].lower()
                entries.setdefault(key, set()).add(role)
        self.thread_entries = entries
        roles: Dict[FuncKey, Set[str]] = {
            k: set(v) for k, v in entries.items()}
        work = list(entries)
        while work:
            f = work.pop()
            r = roles.get(f)
            if not r:
                continue
            for edge in self.calls.get(f, ()):
                if edge.callee in entries:
                    continue
                cur = roles.setdefault(edge.callee, set())
                if not r <= cur:
                    cur |= r
                    work.append(edge.callee)
        # main-reachability: seeds are functions nobody in the project
        # calls (public API, handlers invoked by frameworks) that are
        # not spawn targets; flows forward, never into entries
        callers: Set[FuncKey] = set()
        for edges in self.calls.values():
            for e in edges:
                callers.add(e.callee)
        main: Set[FuncKey] = set()
        work = [f for f in self.functions
                if f not in entries and f not in callers]
        while work:
            f = work.pop()
            if f in main:
                continue
            main.add(f)
            for edge in self.calls.get(f, ()):
                if edge.callee not in entries and edge.callee not in main:
                    work.append(edge.callee)
        final: Dict[FuncKey, frozenset] = {}
        for f in self.functions:
            r = set(roles.get(f, ()))
            if f in main or not r:
                r.add("main")
            final[f] = frozenset(r)
        self.thread_roles = final

    def _spawn_target(self, ctx: ModuleContext, node: ast.Call
                      ) -> Tuple[Optional[ast.AST], Optional[str]]:
        """(target-callable expr, role-name hint) when ``node`` hands
        a callable to another thread; (None, None) otherwise."""
        fname = ctx.resolve(node.func) or ""
        if fname in self._SPAWN_CTORS:
            target = None
            hint = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name":
                    hint = self._role_from_name(kw.value)
            if target is None and node.args:
                # Timer(interval, fn) / start_new_thread(fn, args)
                tail = fname.rsplit(".", 1)[-1]
                if tail == "Timer" and len(node.args) > 1:
                    target = node.args[1]
                elif tail == "start_new_thread":
                    target = node.args[0]
                elif tail == "Thread":
                    target = node.args[0]
            return target, hint
        # atexit hooks run ON the main thread (after it finishes) —
        # they are an entry point for reachability, but attributing a
        # distinct role would mint false main-vs-atexit race pairs;
        # "main" keeps them conflicting only with real worker threads
        if fname == "atexit.register" and node.args:
            return node.args[0], "main"
        if fname == "signal.signal" and len(node.args) > 1:
            return node.args[1], "signal"
        # executor.submit(fn, ...) — only receivers that NAME a pool,
        # so serving's engine.submit(requests) never misresolves
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("submit", "map") and node.args:
            recv = (_dotted(node.func.value) or "").lower()
            if "pool" in recv or "executor" in recv:
                return node.args[0], "pool"
        return None, None

    @staticmethod
    def _role_from_name(expr: ast.AST) -> Optional[str]:
        """Role from a Thread ``name=`` value: the last dash token of
        the literal prefix (``"zoo-serving-batcher"`` → ``batcher``,
        ``f"zoo-metrics-http:{port}"`` → ``http``)."""
        text = None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            text = expr.value
        elif isinstance(expr, ast.JoinedStr) and expr.values and \
                isinstance(expr.values[0], ast.Constant):
            text = str(expr.values[0].value)
        if not text:
            return None
        token = text.split(":")[0].rstrip("-").rsplit("-", 1)[-1]
        return token.lower() or None

    # ------------------------------------------------------------ facts
    def compute_facts(self) -> Dict[str, Dict]:
        axes = sorted(self.axis_names) if self.axis_names \
            else sorted(CANONICAL_AXES)
        facts: Dict[str, Dict] = {}
        for ctx in self.contexts:
            external = self._external_jitted_for(ctx)
            # seed-resolved jit targets of THIS module too — the
            # bundle must be self-sufficient for a re-parsing worker
            for name, spec in self._seed_jitted.get(
                    ctx.relpath, {}).items():
                external.setdefault(name, spec)
            facts[ctx.relpath] = {
                "traced": {},
                "hot_loop": {},
                "external_jitted": external,
                "rng_consumes": self._rng_call_facts.get(
                    ctx.relpath, {}),
                "axes": axes,
                "axis_constants": dict(self.axis_constants),
                "thread_roles": {},
                "thread_entries": {},
            }
        for (rel, qual), roleset in self.thread_roles.items():
            if rel in facts:
                facts[rel]["thread_roles"][qual] = sorted(roleset)
        for (rel, qual), roleset in self.thread_entries.items():
            if rel in facts:
                facts[rel]["thread_entries"][qual] = sorted(roleset)
        for (rel, qual), (kind, reason) in self._marks_traced.items():
            if rel in facts:
                facts[rel]["traced"][qual] = (kind, reason)
        for (rel, qual), reason in self._marks_hot.items():
            if rel in facts:
                facts[rel]["hot_loop"][qual] = reason
        return facts

    def _external_jitted_for(self, ctx: ModuleContext) -> Dict[str, Dict]:
        """Names in ``ctx`` that denote jit-compiled callables defined
        in OTHER analyzed modules (``from m import step_fn`` and
        ``m.step_fn`` forms)."""
        out: Dict[str, Dict] = {}

        for alias, dotted in ctx.aliases.items():
            if dotted == alias:
                continue
            # from m import f (alias -> "m.f")
            mod, _, fname = dotted.rpartition(".")
            mctx = self.by_module.get(mod)
            if mctx is not None and mctx is not ctx and \
                    fname in mctx.jitted_callables:
                out[alias] = _jit_kw_spec(mctx.jitted_callables[fname])
                continue
            # import m [as alias] (alias -> "m"): expose m.f for every
            # module-level jitted callable f
            mctx = self.by_module.get(dotted)
            if mctx is not None and mctx is not ctx:
                for fname, kws in mctx.jitted_callables.items():
                    if "." not in fname and not fname.startswith("self"):
                        out[f"{alias}.{fname}"] = _jit_kw_spec(kws)
        return out


# ----------------------------------------------------- project rules


_PROJECT_RULE_CLASSES: List[type] = []


def register_project_rule(cls):
    """Class decorator for rules that need the WHOLE project (lock
    graphs); they implement ``check_project(proj) -> List[Finding]``
    instead of per-module visitors."""
    assert cls.rule_id
    _PROJECT_RULE_CLASSES.append(cls)
    return cls


def project_rule_classes() -> List[type]:
    """The registered project-level rules (for --list-rules and the
    docs catalog); rules_graph/rules_race register on import."""
    from analytics_zoo_tpu.analysis import rules_graph  # noqa: F401
    from analytics_zoo_tpu.analysis import rules_race  # noqa: F401
    return list(_PROJECT_RULE_CLASSES)


def project_rule_groups() -> List[List[str]]:
    """Project-rule ids grouped by defining module, module names
    sorted.  Rules that share a per-project memo (the race index
    feeding both RACE016 and ATOM017) live in the same module by
    construction, so a group can run in its own ``--jobs`` worker
    without recomputing a sibling group's memo.  ``rules_race`` —
    the heaviest group — sorts last; ``--jobs`` hands it to the
    parent process and fans the rest over the pool."""
    from analytics_zoo_tpu.analysis import rules_graph  # noqa: F401
    from analytics_zoo_tpu.analysis import rules_race  # noqa: F401
    by_mod: Dict[str, List[str]] = {}
    for cls in _PROJECT_RULE_CLASSES:
        by_mod.setdefault(cls.__module__, []).append(cls.rule_id)
    return [by_mod[m] for m in sorted(by_mod)]


def project_findings(proj: ProjectContext,
                     rule_ids: Optional[Iterable[str]] = None
                     ) -> List[Finding]:
    # rules_graph/rules_race register their project rules on import
    from analytics_zoo_tpu.analysis import rules_graph  # noqa: F401
    from analytics_zoo_tpu.analysis import rules_race  # noqa: F401
    wanted = {r.upper() for r in rule_ids} if rule_ids else None
    out: List[Finding] = []
    for cls in _PROJECT_RULE_CLASSES:
        if wanted is not None and cls.rule_id not in wanted:
            continue
        for f in cls().check_project(proj):
            ctx = proj.by_relpath.get(f.path)
            if ctx is None or not ctx.is_suppressed(f):
                out.append(f)
    return out


def load_project(paths: Sequence[str], root: str = "."
                 ) -> Tuple[ProjectContext, List[str]]:
    """Parse + link a path set WITHOUT running the per-module rules —
    the entry point of the CLI's --explain-comms/--explain-hbm
    reports."""
    from analytics_zoo_tpu.analysis.core import parse_contexts
    contexts, errors = parse_contexts(paths, root=root)
    return ProjectContext(contexts), errors
