"""zoolint v3 rule families — the flow-sensitive typestate checks.

Built on :mod:`analysis.cfg` (statement-level CFG with exception
edges + forward fixpoint engine), fed by the PR 7 project facts
(``ctx.jitted_callables`` carries literal ``donate_argnums`` positions
across module boundaries).  Catalog (docs/static-analysis.md renders
the full entries with their runtime twins):

=========  ==========================================================
DONATE012  use-after-donate: a value passed in a donated position of
           an ``engine_jit``/jit call is read again on some later
           path — a runtime error on TPU, a silent no-op on the CPU
           tier-1 runs (rebinding re-arms; ``.aot``/``.warm`` never
           execute and are exempt)
ACK013     stream-record obligations in ``serving/``: every consumed
           record must be discharged exactly once per ownership path
           (ack / ``dead_letter`` / quarantine / serve / a re-raise
           that reaches the loop boundary — double-discharge and
           leak both fire), and every locally-created
           ``engine.Request`` must reach ``complete``/``fail`` (or
           escape to the engine) on all paths — a leaked Request is
           a client blocked until transport timeout
RES015     exception-path resource release: acquisitions with a
           release obligation — breaker half-open probe slots
           (``allow()`` → ``record_success``/``record_failure``),
           manually ``.acquire()``d locks/semaphores, spawned
           processes and non-daemon threads — not discharged on
           every outgoing edge, exception edges INCLUDED
           (generalizing LOCK010's ``with``-only view)
=========  ==========================================================

All three are ``check_module`` rules: they pre-filter cheaply (no
donating callables / not under ``serving/`` / no acquire-ish call in
the source → no CFG is ever built), so the full-repo gate stays
within the PR 7 wall-time envelope.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from analytics_zoo_tpu.analysis.cfg import (
    EXC, FALSE, NEXT, TRUE, CFG, CFGNode, State, build_cfg,
    run_forward)
from analytics_zoo_tpu.analysis.core import (
    ModuleContext, Rule, _dotted, donated_positions, register_rule)

#: abstract obligation facts
OWNED = "owned"
DONE = "done"
ESCAPED = "escaped"


# ---------------------------------------------------------------- helpers


def _pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def _end_pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", getattr(node, "lineno", 0)),
            getattr(node, "end_col_offset",
                    getattr(node, "col_offset", 0)))


def _walk_evaluated(roots: Sequence[ast.AST]):
    """Walk the expression roots, PRUNING nested function/class
    bodies: a ``def``/``lambda`` inside a statement is *defined*
    there, not run — scanning its body at the definition site would
    poison/read/discharge state for code that executes later, if
    ever (the same asymmetry ``cfg._stmt_can_raise`` keeps).
    Decorators (and lambda argument defaults) DO evaluate at the
    definition and stay in the walk."""
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            stack.extend(node.decorator_list)
            if isinstance(node, ast.ClassDef):
                stack.extend(node.bases)
                stack.extend(kw.value for kw in node.keywords)
            else:
                stack.extend(node.args.defaults)
                stack.extend(d for d in node.args.kw_defaults if d)
            continue
        if isinstance(node, ast.Lambda):
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d)
            continue
        stack.extend(ast.iter_child_nodes(node))


def _dotted_loads(exprs: Sequence[ast.AST]
                  ) -> List[Tuple[str, ast.AST]]:
    """Every dotted Load read in the expression roots — full chains
    AND their prefixes (reading ``self._tokens.shape`` reads
    ``self._tokens``), each with its ast node for positions."""
    out: List[Tuple[str, ast.AST]] = []
    for sub in _walk_evaluated(exprs):
        if isinstance(sub, (ast.Name, ast.Attribute)) and \
                isinstance(getattr(sub, "ctx", None), ast.Load):
            d = _dotted(sub)
            if d:
                out.append((d, sub))
    return out


def _bind_names(t: ast.AST, names: Set[str]) -> None:
    """Collect the dotted names a binding TARGET binds — plain names
    and attribute chains (``self._tokens``) both re-arm; subscript
    stores mutate, they don't rebind."""
    if isinstance(t, (ast.Name, ast.Attribute)):
        d = _dotted(t)
        if d:
            names.add(d)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _bind_names(e, names)
    elif isinstance(t, ast.Starred):
        _bind_names(t.value, names)


def _binding_targets(stmt: ast.AST) -> Set[str]:
    names: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            _bind_names(t, names)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        _bind_names(stmt.target, names)
    elif isinstance(stmt, ast.NamedExpr):
        _bind_names(stmt.target, names)
    return names


def _loop_targets(stmt: ast.AST) -> Set[str]:
    names: Set[str] = set()
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        _bind_names(stmt.target, names)
    return names


def _method_call(exprs: Sequence[ast.AST], var: str,
                 attrs: Set[str]) -> Optional[ast.Call]:
    """The first ``var.attr(...)`` call in the expressions with
    ``attr`` in ``attrs`` (receiver must be the bare Name)."""
    for call in _calls_in(exprs):
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in attrs and \
                isinstance(f.value, ast.Name) and f.value.id == var:
            return call
    return None


def _calls_in(exprs: Sequence[ast.AST]) -> List[ast.Call]:
    return [sub for sub in _walk_evaluated(exprs)
            if isinstance(sub, ast.Call)]


def _contains_name(expr: ast.AST, name: str) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id == name
               for sub in _walk_evaluated([expr]))


def _cfg_for(ctx: ModuleContext, fn: ast.AST) -> CFG:
    """One CFG per function per module run, shared by the three
    rules (cached on the context)."""
    cache = getattr(ctx, "_zoolint_cfgs", None)
    if cache is None:
        cache = {}
        ctx._zoolint_cfgs = cache
    cfg = cache.get(id(fn))
    if cfg is None:
        cfg = build_cfg(fn)
        cache[id(fn)] = cfg
    return cfg


def _functions(ctx: ModuleContext) -> List[ast.AST]:
    return [fn for fn in ctx.functions
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _closure_reads(ctx: ModuleContext, fn: ast.AST,
                   names: Set[str]) -> Set[str]:
    """Which of ``names`` are read inside a scope nested in ``fn``
    (a lambda/def closing over them) — those escape tracking."""
    if not names:
        return set()
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and sub.id in names and \
                ctx.enclosing_function(sub) is not fn:
            out.add(sub.id)
    return out


def _truthy_edges(test: ast.AST) -> Optional[Tuple[str, str]]:
    """(edge-when-call-truthy, edge-when-falsy) when the If test is a
    bare ``call(...)`` / ``not call(...)``; None for anything else."""
    if isinstance(test, ast.Call):
        return (TRUE, FALSE)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Call):
        return (FALSE, TRUE)
    return None


def _bare_test_call(test: ast.AST) -> Optional[ast.Call]:
    if isinstance(test, ast.Call):
        return test
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Call):
        return test.operand
    return None


def _escapes(ctx: ModuleContext, exprs: Sequence[ast.AST],
             var: str) -> bool:
    """Does ``var`` escape in these expressions — passed as a call
    argument, returned/yielded, or stored into an attribute/subscript
    target?  Receiver-position uses (``var.fail()``, ``var.done``)
    are not escapes."""
    for root in exprs:
        for sub in _walk_evaluated([root]):
            if not (isinstance(sub, ast.Name) and sub.id == var
                    and isinstance(sub.ctx, ast.Load)):
                continue
            cur: Optional[ast.AST] = sub
            parent = ctx.parent(cur)
            while isinstance(parent, ast.Attribute):
                cur, parent = parent, ctx.parent(parent)
            while parent is not None:
                if isinstance(parent, ast.Call):
                    if cur is not parent.func:
                        return True
                    cur, parent = parent, ctx.parent(parent)
                    continue
                if isinstance(parent, (ast.Return, ast.Yield,
                                       ast.YieldFrom)):
                    return True
                if isinstance(parent, ast.Assign) and \
                        cur is parent.value and any(
                            isinstance(t, (ast.Attribute,
                                           ast.Subscript))
                            for t in parent.targets):
                    return True
                if isinstance(parent, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.Lambda)) or \
                        isinstance(parent, ast.stmt):
                    break
                cur, parent = parent, ctx.parent(parent)
    return False


# ================================================================ DONATE012


@register_rule
class UseAfterDonateRule(Rule):
    """Reading a buffer after it was donated to a jit call.

    Why: ``donate_argnums`` hands the argument's buffer to XLA — on
    TPU the input array is *gone* the moment the call dispatches, and
    touching it afterwards is a runtime error.  On CPU donation is a
    no-op, so the tier-1 suite can never fail on this: the single
    worst TPU-native bug class is invisible to every test this repo
    can run.  Flow-sensitive: flagged when a donated value is read on
    SOME later path (exception edges included — a donating call that
    raises may already have consumed its buffers, which is why
    ``DecodeSlotPool`` rebuilds state in its handlers).  Rebinding
    re-arms the name (``params, opt = step(params, opt)`` is the
    sanctioned pattern); ``.warm(...)``/``.aot(...)`` pre-lower
    without executing and never donate.
    """

    rule_id = "DONATE012"
    severity = "error"
    doc = ("use-after-donate: a buffer passed in a donated position "
           "of a jit call is read again on some path (silent on CPU, "
           "fatal on TPU)")

    def check_module(self, ctx: ModuleContext) -> None:
        donating: Dict[str, Set[int]] = {}
        for name, kws in ctx.jitted_callables.items():
            pos = donated_positions(kws)
            if pos:   # None (unmappable) and {} (no donation) exempt
                donating[name] = pos
        if not donating:
            return
        for fn in _functions(ctx):
            if any(_dotted(c.func) in donating
                   for c in ast.walk(fn) if isinstance(c, ast.Call)):
                self._check_function(ctx, fn, donating)

    # ------------------------------------------------------------ per-fn
    def _donate_events(self, node: CFGNode, donating: Dict[str, Set[int]]
                       ) -> List[Tuple[Tuple[int, int], ast.Call, str,
                                       List[str]]]:
        """(completion pos, call, callee, donated arg names) for every
        donating call in this node — completion position is the END
        of the call: its arguments are read before the buffers are
        consumed."""
        out = []
        for call in _calls_in(node.exprs):
            target = _dotted(call.func)
            pos = donating.get(target or "")
            if not pos:
                continue
            names = []
            for i, arg in enumerate(call.args):
                if i in pos:
                    d = _dotted(arg)
                    if d:
                        names.append(d)
            if names:
                out.append((_end_pos(call), call, target, names))
        return out

    def _check_function(self, ctx: ModuleContext, fn: ast.AST,
                        donating: Dict[str, Set[int]]) -> None:
        cfg = _cfg_for(ctx, fn)

        def transfer(node: CFGNode, state: State
                     ) -> Dict[Optional[str], State]:
            events = self._donate_events(node, donating)
            if not events and node.kind not in ("stmt", "for", "with"):
                return {None: state}
            poisoned = dict(state)
            for _p, call, target, names in events:
                for name in names:
                    poisoned[name] = poisoned.get(
                        name, frozenset()) | {(call.lineno, target)}
            out: Dict[Optional[str], State] = {EXC: poisoned}
            bound: Set[str] = set()
            if node.kind == "stmt" and node.stmt is not None:
                bound = _binding_targets(node.stmt)
            elif node.kind == "with" and node.stmt is not None:
                for item in node.stmt.items:
                    if item.optional_vars is not None:
                        bound |= _binding_targets(
                            ast.Assign(targets=[item.optional_vars],
                                       value=ast.Constant(value=None)))
            rebound = {k: v for k, v in poisoned.items()
                       if k not in bound}
            out[None] = rebound
            if node.kind == "for" and node.stmt is not None:
                loop_bound = _loop_targets(node.stmt)
                out[TRUE] = {k: v for k, v in poisoned.items()
                             if k not in loop_bound}
            return out

        in_states = run_forward(cfg, {}, transfer)

        reported: Set[Tuple[int, int, str]] = set()
        for node in cfg.nodes:
            state = in_states.get(node.idx)
            if state is None or not node.exprs:
                continue
            events = self._donate_events(node, donating)
            marks = sorted((pos, target, name)
                           for pos, _c, target, names in events
                           for name in names)
            working = dict(state)
            reads = sorted(((_pos(n), d, n)
                            for d, n in _dotted_loads(node.exprs)),
                           key=lambda t: t[0])
            mi = 0
            for rpos, dname, rnode in reads:
                while mi < len(marks) and marks[mi][0] <= rpos:
                    _p, target, name = marks[mi]
                    working[name] = working.get(
                        name, frozenset()) | {(node.line, target)}
                    mi += 1
                hits = working.get(dname)
                if not hits:
                    continue
                key = (rnode.lineno, rnode.col_offset, dname)
                if key in reported:
                    continue
                reported.add(key)
                dline, target = sorted(hits)[0]
                self.report(
                    rnode,
                    f"'{dname}' was donated to jitted '{target}' "
                    f"(line {dline}) and is read again here — on TPU "
                    f"the donated buffer no longer exists (CPU runs "
                    f"hide this: donation is a no-op off-accelerator)."
                    f" Rebind it from the call's result, or drop it "
                    f"from donate_argnums",
                    line=rnode.lineno)


# ================================================================= ACK013


#: call-name tails that discharge a consumed stream record / leased
#: shard (the batchjobs ledger settles by commit or release)
_ACK_NAMES = {
    "xack", "ack", "_ack", "dead_letter", "_dead_letter",
    "quarantine", "_quarantine",
    "commit_shard", "_commit_shard", "release_shard",
    "_release_shard",
}
#: claim sources: reading one of these hands the caller records it
#: now OWES an ack for (XREADGROUP delivers exactly-once; XAUTOCLAIM
#: re-delivers another worker's pending entries; claim_shards leases
#: batch shards that must be committed or released)
_CLAIM_NAMES = {"xreadgroup", "xautoclaim", "claim_shards"}


@register_rule
class AckObligationRule(Rule):
    """Exactly-once discharge of consumed stream records + the
    ``engine.Request`` completion contract, in ``serving/`` — and the
    same obligation over leased batch shards in ``batchjobs/``.

    Why: every protocol bug the chaos/storm harnesses caught lately
    was a *path-sensitive obligation* bug — a record claimed on one
    path and never discharged (or discharged twice) on another.  A
    consumed record that completes an iteration without ack /
    ``dead_letter`` / quarantine / serve stays pending forever and
    feeds the poison-attempt ledger blame it never earned (the PR 13
    reclaim defect quarantined INNOCENT records exactly this way —
    its fixture lives in the test suite); a double discharge
    overwrites a delivered result with an error.  A locally-created
    ``Request`` that can reach function exit without ``complete()``/
    ``fail()``/escaping to the engine is a client blocked until its
    transport timeout.  A path that ends in a propagating raise is
    NOT a leak: the Redis loop dying un-acked IS the PEL-reclaim
    contract ("a re-raise that reaches the loop boundary").

    The batchjobs claim→settle loop carries the identical shape: a
    shard returned by ``claim_shards`` must reach ``commit_shard`` /
    ``release_shard`` or propagate a raise on every path — a shard
    that completes an iteration still OWNED is leased-but-never-
    settled, invisible to peers until the lease times out, and a
    double settle is the duplicate-commit race the O_EXCL marker
    exists to absorb.  Same rule, second ledger, so ``batchjobs/`` is
    in scope too.
    """

    rule_id = "ACK013"
    severity = "error"
    doc = ("serving/batchjobs obligation: consumed record or leased "
           "shard not discharged exactly once, or a Request that can "
           "miss complete()/fail() on some path")

    SCOPE = ("analytics_zoo_tpu/serving/",
             "analytics_zoo_tpu/batchjobs/")

    def check_module(self, ctx: ModuleContext) -> None:
        if not ctx.relpath.startswith(self.SCOPE):
            return
        for fn in _functions(ctx):
            self._check_requests(ctx, fn)
            self._check_records(ctx, fn)

    # ---------------------------------------------------------- requests
    def _request_creations(self, ctx: ModuleContext, fn: ast.AST
                           ) -> Dict[int, Tuple[str, ast.Assign]]:
        """id(stmt) -> (var, stmt) for ``r = Request(...)``."""
        out: Dict[int, Tuple[str, ast.Assign]] = {}
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)):
                continue
            if ctx.enclosing_function(sub) is not fn:
                continue
            resolved = ctx.resolve(sub.value.func) or ""
            if resolved == "Request" or resolved.endswith(".Request"):
                out[id(sub)] = (sub.targets[0].id, sub)
        return out

    def _check_requests(self, ctx: ModuleContext, fn: ast.AST) -> None:
        creations = self._request_creations(ctx, fn)
        if not creations:
            return
        names = {var for var, _s in creations.values()}
        captured = _closure_reads(ctx, fn, names)
        cfg = _cfg_for(ctx, fn)

        def transfer(node: CFGNode, state: State
                     ) -> Dict[Optional[str], State]:
            out = dict(state)
            exc_out = dict(state)
            per_edge: Dict[Optional[str], State] = {}
            # guards refine: if X.done / if not X.done
            if node.kind == "if" and node.stmt is not None:
                test = node.stmt.test
                recv = None
                positive = True
                if isinstance(test, ast.UnaryOp) and \
                        isinstance(test.op, ast.Not):
                    test, positive = test.operand, False
                if isinstance(test, ast.Attribute) and \
                        test.attr == "done" and \
                        isinstance(test.value, ast.Name) and \
                        test.value.id in names:
                    recv = f"req:{test.value.id}"
                if recv is not None and recv in state:
                    done_state = (state[recv] - {OWNED}) | {DONE}
                    not_done = state[recv] - {DONE}
                    per_edge[TRUE if positive else FALSE] = {
                        **out, recv: done_state}
                    per_edge[FALSE if positive else TRUE] = {
                        **out, recv: not_done}
                    per_edge[None] = out
                    return per_edge
            for var in names:
                if var in captured:
                    continue
                key = f"req:{var}"
                # creation arms on the normal edge only (a raising
                # constructor never produced the object)
                if node.kind == "stmt" and \
                        id(node.stmt) in creations and \
                        creations[id(node.stmt)][0] == var:
                    out[key] = frozenset({OWNED})
                    continue
                if key not in out:
                    continue
                call = _method_call(node.exprs, var,
                                    {"complete", "fail"})
                if call is not None:
                    if DONE in out[key]:
                        self._double(ctx, call, var)
                    out[key] = (out[key] - {OWNED}) | {DONE}
                    exc_out[key] = out[key]
                elif _escapes(ctx, node.exprs, var):
                    out[key] = (out[key] - {OWNED}) | {ESCAPED}
                    exc_out[key] = out[key]
                # rebinding the name drops the old obligation's
                # tracking (the object is unreachable — still a
                # leak semantically, but untrackable; precision
                # over recall)
                if node.kind == "stmt" and node.stmt is not None and \
                        var in _binding_targets(node.stmt) and \
                        id(node.stmt) not in creations:
                    out.pop(key, None)
            per_edge[None] = out
            per_edge[EXC] = exc_out
            return per_edge

        in_states = run_forward(cfg, {}, transfer)
        exit_state = in_states.get(cfg.exit, {})
        for var, stmt in creations.values():
            if var in captured:
                continue
            if OWNED in exit_state.get(f"req:{var}", frozenset()):
                self.report(
                    stmt,
                    f"Request '{var}' can reach function exit without "
                    f"complete()/fail() and without being handed to "
                    f"the engine — its client blocks until the "
                    f"transport timeout (discharge it on every "
                    f"non-raising path)")

    def _double(self, ctx: ModuleContext, call: ast.Call,
                var: str) -> None:
        key = (call.lineno, call.col_offset, var)
        if not hasattr(self, "_doubles"):
            self._doubles: Set[Tuple[int, int, str]] = set()
        if key in self._doubles:
            return
        self._doubles.add(key)
        self.report(
            call,
            f"Request '{var}' may already be completed/failed on this "
            f"path — a second discharge overwrites the delivered "
            f"outcome (guard with 'if not {var}.done:')")

    # ----------------------------------------------------------- records
    def _claim_vars(self, ctx: ModuleContext, fn: ast.AST) -> Set[str]:
        """Names holding records consumed from a claim source
        (xreadgroup/xautoclaim), chased through filter rebinds."""
        claimed: Set[str] = set()
        assigns = [s for s in ast.walk(fn)
                   if isinstance(s, ast.Assign)
                   and ctx.enclosing_function(s) is fn]
        changed = True
        while changed:
            changed = False
            for s in assigns:
                tgt = s.targets[0] if len(s.targets) == 1 else None
                if not isinstance(tgt, ast.Name) or \
                        tgt.id in claimed:
                    continue
                src = s.value
                is_claim = (isinstance(src, ast.Call)
                            and isinstance(src.func, ast.Attribute)
                            and src.func.attr in _CLAIM_NAMES)
                derives = any(_contains_name(src, c) for c in claimed)
                if is_claim or derives:
                    claimed.add(tgt.id)
                    changed = True
        return claimed

    def _record_loops(self, ctx: ModuleContext, fn: ast.AST
                      ) -> List[Tuple[ast.For, str, Optional[str]]]:
        """(loop, id-var, fields-var) for every ``for`` over claimed
        records; the id var (the first loop-target element, or the
        bare target) is what discharge calls must mention — acks go
        by entry id — and the fields var (second element, when the
        target unpacks) is what distinguishes a SETTLEMENT from an
        inspection."""
        claimed = self._claim_vars(ctx, fn)
        out: List[Tuple[ast.For, str, Optional[str]]] = []
        for sub in ast.walk(fn):
            if not isinstance(sub, (ast.For, ast.AsyncFor)):
                continue
            it = sub.iter
            over_claim = (isinstance(it, ast.Name)
                          and it.id in claimed) or (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Attribute)
                and it.func.attr in _CLAIM_NAMES)
            if not over_claim:
                continue
            tgt = sub.target
            if isinstance(tgt, ast.Tuple) and tgt.elts and \
                    isinstance(tgt.elts[0], ast.Name):
                fields = tgt.elts[1].id if (
                    len(tgt.elts) > 1
                    and isinstance(tgt.elts[1], ast.Name)) else None
                out.append((sub, tgt.elts[0].id, fields))
            elif isinstance(tgt, ast.Name):
                out.append((sub, tgt.id, None))
        return out

    def _discharging_call(self, ctx: ModuleContext, fn: ast.AST,
                          call: ast.Call, id_name: str,
                          fields_name: Optional[str]) -> bool:
        """Does this call discharge the record ``id_name``?  The
        discharge-vocabulary names (ack/dead-letter/quarantine
        family) discharge with the id alone — acks go by entry id.
        An ownership TRANSFER to a ``self.``-method / local function
        must carry the record's PAYLOAD too (the fields var, when
        the loop unpacks one): settling a record needs its data,
        while an inspection/logging helper typically takes only the
        key — treating those as discharges minted spurious
        double-settle findings.  Builtins and unresolvable calls
        never discharge."""
        args = list(call.args) + [kw.value for kw in call.keywords]
        if not any(_contains_name(a, id_name) for a in args):
            return False
        f = call.func
        vocab = (f.attr if isinstance(f, ast.Attribute) else
                 f.id if isinstance(f, ast.Name) else None)
        if vocab in _ACK_NAMES:
            return True
        if fields_name is not None and \
                not any(_contains_name(a, fields_name) for a in args):
            return False
        if isinstance(f, ast.Attribute):
            d = _dotted(f)
            return bool(d and d.count(".") == 1 and
                        d.split(".")[0] in ("self", "cls"))
        if isinstance(f, ast.Name):
            return ctx._local_function_named(call, f.id) is not None
        return False

    def _check_records(self, ctx: ModuleContext, fn: ast.AST) -> None:
        loops = self._record_loops(ctx, fn)
        if not loops:
            return
        cfg = _cfg_for(ctx, fn)
        keys = {id(loop): (f"rec:{i}", id_name, fields_name)
                for i, (loop, id_name, fields_name)
                in enumerate(loops)}
        by_id = {id(loop): loop for loop, _n, _f in loops}
        doubles: Set[Tuple[int, int]] = set()

        def transfer(node: CFGNode, state: State
                     ) -> Dict[Optional[str], State]:
            out = dict(state)
            per_edge: Dict[Optional[str], State] = {}
            if node.kind == "for" and id(node.stmt) in keys:
                key = keys[id(node.stmt)][0]
                armed = dict(out)
                armed[key] = frozenset({OWNED})
                per_edge[TRUE] = armed
                per_edge[None] = out
                return per_edge
            # a guard call that takes the record discharges it on the
            # path where it answers truthy (the already-served /
            # already-handled idiom)
            if node.kind == "if" and node.stmt is not None:
                edges = _truthy_edges(node.stmt.test)
                call = _bare_test_call(node.stmt.test)
                if edges and call is not None:
                    for key, id_name, fields_name in keys.values():
                        if key in out and self._discharging_call(
                                ctx, fn, call, id_name, fields_name):
                            t_state = dict(out)
                            t_state[key] = \
                                (out[key] - {OWNED}) | {DONE}
                            per_edge[edges[0]] = t_state
                            per_edge[edges[1]] = out
                            per_edge[None] = out
                            return per_edge
            exc_out = dict(out)
            for key, id_name, fields_name in keys.values():
                if key not in out:
                    continue
                for call in _calls_in(node.exprs):
                    if self._discharging_call(ctx, fn, call, id_name,
                                              fields_name):
                        if DONE in out[key]:
                            pos = (call.lineno, call.col_offset)
                            if pos not in doubles:
                                doubles.add(pos)
                                self.report(
                                    call,
                                    f"record '{id_name}' may already "
                                    f"be discharged on this path — a "
                                    f"second ack/judgment here double-"
                                    f"settles it (the PR 13 reclaim "
                                    f"class: an error result can "
                                    f"overwrite a delivered one)")
                        out[key] = (out[key] - {OWNED}) | {DONE}
                        # the discharge RAISING keeps the obligation:
                        # a swallowed broker failure leaves the record
                        # un-discharged on the handler path
            per_edge[None] = out
            per_edge[EXC] = exc_out
            return per_edge

        in_states = run_forward(cfg, {}, transfer)
        loops_by_key = {keys[i][0]: (by_id[i], keys[i][1])
                        for i in keys}
        leaked: Set[str] = set()
        for node in cfg.nodes:
            if node.kind != "for" or id(node.stmt) not in keys:
                continue
            key, id_name, _fields = keys[id(node.stmt)]
            state = in_states.get(node.idx, {})
            if OWNED in state.get(key, frozenset()) and \
                    key not in leaked:
                leaked.add(key)
                self.report(
                    by_id[id(node.stmt)],
                    f"record '{id_name}' consumed from the stream can "
                    f"complete an iteration without ack/dead_letter/"
                    f"serve on some path — it stays pending forever "
                    f"and accumulates poison-attempt blame (discharge "
                    f"it, or let the exception propagate to the loop "
                    f"boundary)")
        exit_state = in_states.get(cfg.exit, {})
        for key, (loop, id_name) in loops_by_key.items():
            if key in leaked:
                continue
            if OWNED in exit_state.get(key, frozenset()):
                leaked.add(key)
                self.report(
                    loop,
                    f"record '{id_name}' consumed from the stream can "
                    f"reach function exit without ack/dead_letter/"
                    f"serve on some path (break/early-return without "
                    f"discharging)")


# ================================================================= RES015


_PROC_DISCHARGE = {"wait", "communicate", "terminate", "kill"}


@register_rule
class ExceptionPathReleaseRule(Rule):
    """Acquire/release obligations checked on EVERY outgoing edge —
    exception edges included.

    Why: LOCK010 sees only ``with``-scoped locking; the bugs that
    actually shipped were *manual* protocols — the PR 9 breaker
    half-open probe slot leaked on a command-error re-raise path,
    wedging the breaker HALF_OPEN forever while readiness read ok.
    Tracked obligations: a claimed breaker probe slot
    (``X.allow()`` truthy → ``X.record_success()``/
    ``X.record_failure()`` on every path, propagating raises
    included), a manually ``.acquire()``d lock/semaphore (must reach
    ``.release()``), a spawned ``subprocess.Popen`` (must be
    waited/terminated or handed off — else a zombie), and a
    ``.start()``ed non-daemon ``threading.Thread`` (must be joined or
    handed off — else interpreter exit blocks).  ``with`` remains the
    preferred form; this rule covers what ``with`` cannot express.
    """

    rule_id = "RES015"
    severity = "warning"
    doc = ("resource acquired (probe slot / .acquire() / Popen / "
           "non-daemon Thread) can leak on an exception or "
           "early-exit path")

    def check_module(self, ctx: ModuleContext) -> None:
        src = ctx.source
        if not (".allow(" in src or ".acquire(" in src
                or "Popen(" in src or "Thread(" in src):
            return
        for fn in _functions(ctx):
            self._check_function(ctx, fn)

    # ------------------------------------------------------------ shapes
    @staticmethod
    def _lockish(recv: str) -> bool:
        tail = recv.rsplit(".", 1)[-1].lower()
        return "lock" in tail or "sem" in tail or "mutex" in tail

    @staticmethod
    def _breakerish(recv: str) -> bool:
        return "breaker" in recv.rsplit(".", 1)[-1].lower()

    def _recv_call(self, exprs: Sequence[ast.AST], attr: str,
                   pred) -> Optional[Tuple[str, ast.Call]]:
        for call in _calls_in(exprs):
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == attr:
                recv = _dotted(f.value)
                if recv and pred(recv):
                    return recv, call
        return None

    def _thread_creations(self, ctx: ModuleContext, fn: ast.AST
                          ) -> Set[str]:
        """Local names bound to a NON-daemon threading.Thread —
        daemonized either by the constructor keyword or by the
        ``t.daemon = True`` attribute form."""
        out: Set[str] = set()
        daemonized: Set[str] = set()
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1):
                continue
            tgt = sub.targets[0]
            if isinstance(tgt, ast.Attribute) and \
                    tgt.attr == "daemon" and \
                    isinstance(tgt.value, ast.Name) and \
                    isinstance(sub.value, ast.Constant) and \
                    sub.value.value:
                daemonized.add(tgt.value.id)
                continue
            if not (isinstance(tgt, ast.Name)
                    and isinstance(sub.value, ast.Call)):
                continue
            resolved = ctx.resolve(sub.value.func) or ""
            if resolved != "threading.Thread" and \
                    not resolved.endswith(".Thread"):
                continue
            daemon = next((kw.value for kw in sub.value.keywords
                           if kw.arg == "daemon"), None)
            if isinstance(daemon, ast.Constant) and daemon.value:
                continue
            out.add(tgt.id)
        return out - daemonized

    def _popen_creations(self, ctx: ModuleContext, fn: ast.AST
                         ) -> Dict[int, str]:
        out: Dict[int, str] = {}
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)):
                continue
            resolved = ctx.resolve(sub.value.func) or ""
            if resolved == "subprocess.Popen" or \
                    resolved.endswith(".Popen"):
                out[id(sub)] = sub.targets[0].id
        return out

    # ------------------------------------------------------------- check
    def _check_function(self, ctx: ModuleContext, fn: ast.AST) -> None:
        threads = self._thread_creations(ctx, fn)
        popens = self._popen_creations(ctx, fn)
        has_probe = any(
            isinstance(c.func, ast.Attribute) and c.func.attr == "allow"
            and _dotted(c.func.value)
            and self._breakerish(_dotted(c.func.value))
            for c in ast.walk(fn) if isinstance(c, ast.Call))
        has_acquire = any(
            isinstance(c.func, ast.Attribute)
            and c.func.attr == "acquire" and _dotted(c.func.value)
            and self._lockish(_dotted(c.func.value))
            for c in ast.walk(fn) if isinstance(c, ast.Call))
        if not (threads or popens or has_probe or has_acquire):
            return
        captured = _closure_reads(ctx, fn, threads | set(
            popens.values()))
        sites: Dict[str, ast.AST] = {}
        #: obligation key -> the Name its acquiring call's result was
        #: bound to (``ok = b.allow()``) — a later ``if ok:`` / ``if
        #: not ok:`` refines: nothing was acquired on the falsy arm
        guard_vars: Dict[str, str] = {}
        cfg = _cfg_for(ctx, fn)

        def transfer(node: CFGNode, state: State
                     ) -> Dict[Optional[str], State]:
            out = dict(state)
            per_edge: Dict[Optional[str], State] = {}
            if node.kind == "if" and node.stmt is not None:
                # guard-variable refinement: the bound result of the
                # acquiring call decides whether anything was acquired
                test = node.stmt.test
                positive = True
                if isinstance(test, ast.UnaryOp) and \
                        isinstance(test.op, ast.Not):
                    test, positive = test.operand, False
                if isinstance(test, ast.Name):
                    doomed = [k for k, v in guard_vars.items()
                              if v == test.id and k in out]
                    if doomed:
                        falsy = dict(out)
                        for k in doomed:
                            falsy.pop(k)
                        per_edge[FALSE if positive else TRUE] = falsy
                        per_edge[TRUE if positive else FALSE] = out
                        per_edge[None] = out
                        return per_edge
            # breaker probe / manual acquire in an if-test arm on the
            # truthy edge only (``if not b.allow(): raise`` claims no
            # slot on the raising arm)
            if node.kind == "if" and node.stmt is not None:
                edges = _truthy_edges(node.stmt.test)
                call = _bare_test_call(node.stmt.test)
                if edges and isinstance(
                        getattr(call, "func", None), ast.Attribute):
                    attr = call.func.attr
                    recv = _dotted(call.func.value)
                    key = None
                    if attr == "allow" and recv and \
                            self._breakerish(recv):
                        key = f"probe:{recv}"
                    elif attr == "acquire" and recv and \
                            self._lockish(recv):
                        key = f"lock:{recv}"
                    if key is not None:
                        sites.setdefault(key, call)
                        armed = dict(out)
                        armed[key] = frozenset({OWNED})
                        per_edge[edges[0]] = armed
                        per_edge[edges[1]] = out
                        per_edge[None] = out
                        return per_edge
            exc_keeps = dict(out)

            def note_guard(key: str, call: ast.Call) -> None:
                # ``ok = X.allow()`` / ``got = lock.acquire(False)``:
                # remember the bound name so a later ``if ok:`` can
                # prove the falsy arm acquired nothing
                stmt = node.stmt
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        stmt.value is call:
                    guard_vars[key] = stmt.targets[0].id

            # statement-position acquisitions arm on the normal edge
            hit = self._recv_call(node.exprs, "allow", self._breakerish)
            if hit is not None:
                recv, call = hit
                sites.setdefault(f"probe:{recv}", call)
                out[f"probe:{recv}"] = frozenset({OWNED})
                note_guard(f"probe:{recv}", call)
            hit = self._recv_call(node.exprs, "acquire", self._lockish)
            if hit is not None:
                recv, call = hit
                sites.setdefault(f"lock:{recv}", call)
                out[f"lock:{recv}"] = frozenset({OWNED})
                note_guard(f"lock:{recv}", call)
            if node.kind == "stmt" and id(node.stmt) in popens:
                var = popens[id(node.stmt)]
                if var not in captured:
                    sites.setdefault(f"proc:{var}", node.stmt)
                    out[f"proc:{var}"] = frozenset({OWNED})
            for var in threads:
                if var in captured:
                    continue
                if _method_call(node.exprs, var, {"start"}):
                    sites.setdefault(f"thread:{var}", node.stmt
                                     or node.exprs[0])
                    out[f"thread:{var}"] = frozenset({OWNED})
            # discharges (apply on every edge: a release that raises
            # still released first in every pattern this models)
            for key in list(out):
                kind, _, name = key.partition(":")
                done = False
                if kind == "probe":
                    done = bool(
                        self._recv_is(node.exprs, name,
                                      {"record_success",
                                       "record_failure"}))
                elif kind == "lock":
                    done = bool(self._recv_is(node.exprs, name,
                                              {"release"}))
                elif kind == "proc":
                    done = bool(
                        _method_call(node.exprs, name,
                                     _PROC_DISCHARGE)) or \
                        _escapes(ctx, node.exprs, name)
                elif kind == "thread":
                    done = bool(_method_call(node.exprs, name,
                                             {"join"})) or \
                        _escapes(ctx, node.exprs, name)
                if done:
                    out[key] = (out[key] - {OWNED}) | {DONE}
                    if key in exc_keeps:
                        exc_keeps[key] = out[key]
                # an acquisition armed by THIS node stays absent from
                # the exception-edge state: the acquiring call raising
                # means nothing was acquired
            per_edge[None] = out
            per_edge[EXC] = exc_keeps
            return per_edge

        in_states = run_forward(cfg, {}, transfer)
        messages = {
            "probe": ("half-open probe slot claimed by {n}.allow() is "
                      "not released on some path — record_success()/"
                      "record_failure() must run on every outcome, "
                      "exception edges included (a leaked slot wedges "
                      "the breaker HALF_OPEN forever: the PR 9 class)"),
            "lock": ("'{n}' is .acquire()d but a path exits without "
                     ".release() — every thread behind it deadlocks "
                     "(prefer 'with {n}:'; this is the manual-protocol "
                     "case LOCK010 cannot see)"),
            "proc": ("spawned process '{n}' can leak on some path — "
                     "wait()/communicate()/terminate() it (or hand it "
                     "to a monitor) on every exit, or it zombies"),
            "thread": ("non-daemon thread '{n}' is start()ed but a "
                       "path exits without join() — interpreter "
                       "shutdown blocks on it (join in a finally, or "
                       "mark it daemon)"),
        }
        reported: Set[str] = set()
        for exit_idx in (cfg.exit, cfg.raise_exit):
            state = in_states.get(exit_idx, {})
            for key, facts in state.items():
                if OWNED not in facts or key in reported:
                    continue
                reported.add(key)
                kind, _, name = key.partition(":")
                site = sites.get(key)
                if site is None:
                    continue
                self.report(site, messages[kind].format(n=name))

    @staticmethod
    def _recv_is(exprs: Sequence[ast.AST], recv: str,
                 attrs: Set[str]) -> Optional[ast.Call]:
        for call in _calls_in(exprs):
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in attrs and \
                    _dotted(f.value) == recv:
                return call
        return None
