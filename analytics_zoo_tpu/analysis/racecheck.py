"""zoo-racecheck — deterministic schedule-fuzzing race sanitizer.

The RUNTIME half of zoolint v4's race family.  RACE016 proves a
lockset/role conflict *statically*; this module arms a sanitizer over
opted-in classes and reports the races that actually happen-before-
violate under a *deterministically perturbed* schedule, so every
static finding can be labeled confirmed/unconfirmed and the sanitizer
can be pointed at any tier-1 concurrency test.

Detection model (FastTrack-style happens-before, not sampling):

* every thread carries a **vector clock**; ``Thread.start``/``join``
  draw fork/join edges (so pre-``start()`` initialization is ordered
  and never reported);
* lock ``acquire``/``release`` (including ``with lock:`` enter/exit,
  observed through the ``sys.setprofile`` c_call hook — locks are C
  objects and cannot be monkeypatched) draw release→acquire edges.
  ``queue.Queue``/``Condition``/``Event`` synchronize through an
  internal lock, so the sanctioned hand-off idioms are ordered *for
  free* — no idiom allowlist to drift out of date;
* attribute reads/writes on **opted-in classes** (``arm(watch=...)``
  swaps in instrumented ``__getattribute__``/``__setattr__``) are
  checked against the last write of the same ``(instance, attr)``:
  two WRITES, distinct threads, no happens-before path → violation.
  Write-write is the whole hazard class at attribute-rebind
  granularity: under the GIL a lone read racing one writer is the
  sanctioned monotonic-counter / atomic-swap idiom (static RACE016
  grants the same write×read exemption), while every dangerous
  RACE016 shape — RMW, check-then-act, mutation on ≥2 roles — lands
  a write on each participating thread and surfaces here as an
  unordered write pair.  Reads still take the chaos yield (they are
  the interleaving points that turn a latent lost-update into a
  visible one) but stay out of the ledger.

Determinism: the access ledger is updated under one internal lock
(excluded from the happens-before model), so a racy pair is detected
on EVERY schedule, not just unlucky ones — the CI drill requires
100/100, and pure happens-before needs no "did it actually
interleave" luck.  Schedule fuzzing (seeded ``sys.setswitchinterval``
plus per-thread chaos yields at access points) exists to shake out
*consequences* (torn state, stomped entries) and to vary which access
pair is reported first, not to make detection possible.

Zero cost disarmed: importing this module patches nothing; ``arm()``
installs the hooks and ``disarm()`` restores every original.

CONTRACT: stdlib-only, loadable by file path (``scripts/zoo-racecheck``
runs on control nodes without jax).
"""

from __future__ import annotations

import random
import sys
import threading
import time
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = [
    "Sanitizer", "Violation", "arm", "disarm", "active", "violations",
    "join_static", "racy_fixture", "clean_fixture", "selftest",
]

#: attribute prefixes never tracked (dunders are protocol traffic;
#: ``_rc_`` is this module's own namespace)
_SKIP_PREFIXES = ("__", "_rc_")

#: C method names that mean "this thread acquired/released a lock"
_ACQUIRE_NAMES = ("acquire", "__enter__", "acquire_lock")
_RELEASE_NAMES = ("release", "__exit__", "release_lock")

#: types whose acquire/release draw happens-before edges.  Matched by
#: name so the set works without importing _thread internals.
_LOCK_TYPE_NAMES = ("lock", "RLock", "_RLock")


class Violation:
    """One happens-before violation on ``(class, attr)``."""

    __slots__ = ("cls", "attr", "kind", "thread_a", "thread_b",
                 "site_a", "site_b")

    def __init__(self, cls: str, attr: str, kind: str,
                 thread_a: str, thread_b: str,
                 site_a: str, site_b: str):
        self.cls = cls
        self.attr = attr
        self.kind = kind          # "write-write" (the GIL-level hazard)
        self.thread_a = thread_a
        self.thread_b = thread_b
        self.site_a = site_a
        self.site_b = site_b

    def key(self) -> Tuple[str, str, str]:
        return (self.cls, self.attr, self.kind)

    def to_dict(self) -> Dict[str, str]:
        return {"class": self.cls, "attr": self.attr,
                "kind": self.kind, "thread_a": self.thread_a,
                "thread_b": self.thread_b, "site_a": self.site_a,
                "site_b": self.site_b}

    def __repr__(self) -> str:
        return (f"Violation({self.cls}.{self.attr} {self.kind} "
                f"{self.thread_a}@{self.site_a} vs "
                f"{self.thread_b}@{self.site_b})")


class _VC:
    """Vector clock, thread-name keyed.  Plain dict ops only — every
    mutation happens either under the ledger lock or on state owned
    by exactly one thread (its own clock)."""

    __slots__ = ("c",)

    def __init__(self, c: Optional[Dict[str, int]] = None):
        self.c = dict(c) if c else {}

    def copy(self) -> "_VC":
        return _VC(self.c)

    def tick(self, tid: str) -> None:
        self.c[tid] = self.c.get(tid, 0) + 1

    def join(self, other: "_VC") -> None:
        for k, v in other.c.items():
            if v > self.c.get(k, 0):
                self.c[k] = v

    def covers(self, tid: str, clock: int) -> bool:
        """Does this clock know of ``tid``'s event at ``clock``? —
        the epoch happens-before test."""
        return self.c.get(tid, 0) >= clock


class _AccessRecord:
    """Per ``(instance id, attr)`` ledger entry."""

    __slots__ = ("last_write",)

    def __init__(self):
        # (tid, tid-clock, site)
        self.last_write: Optional[Tuple[str, int, str]] = None


def _site(depth: int) -> str:
    """``file:lineno`` of the access site: the frame above the
    instrumented accessor."""
    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except ValueError:          # pragma: no cover - shallow stack
        return "?"


class Sanitizer:
    """The armed state: instrumented classes, per-thread clocks, the
    access ledger, and the profile/chaos hooks."""

    def __init__(self, *, seed: int = 0, chaos: bool = True,
                 switch_interval: Optional[float] = 1e-5,
                 max_violations: int = 200):
        self.seed = seed
        self.chaos = chaos
        self.switch_interval = switch_interval
        self.max_violations = max_violations
        self._armed = False
        self._ledger_lock = threading.Lock()
        self._patched: List[Tuple[type, Any, Any]] = []
        self._thread_vc: Dict[str, _VC] = {}
        self._lock_vc: Dict[int, _VC] = {}
        self._records: Dict[Tuple[int, str], _AccessRecord] = {}
        self._cls_of: Dict[int, str] = {}     # instance id -> class name
        self._violations: List[Violation] = []
        self._seen: Set[Tuple[str, str, str]] = set()
        self._rngs: Dict[str, random.Random] = {}
        self._tid_seq = 0
        # per-session thread attr names: a thread outliving one
        # arm()/disarm() cycle must not leak its old key or birth
        # clock into the next session's (fresh) clock space
        global _SESSION_SEQ
        # sanitizers are constructed by arm(), which the controlling
        # thread calls BEFORE any instrumented workload threads
        # exist (single-controller contract)
        # zoolint: disable=RACE005 — arm() runs pre-spawn, single-controller contract
        _SESSION_SEQ += 1
        self._tid_attr = f"_rc_tid_{_SESSION_SEQ}"
        self._birth_attr = f"_rc_birth_{_SESSION_SEQ}"
        self._tls = threading.local()
        self._saved_start = None
        self._saved_join = None
        self._saved_switch: Optional[float] = None
        self._saved_profile = None

    # ------------------------------------------------------------ clocks
    def _tid(self) -> str:
        """Unique per-thread key.  NOT ``name#ident``: the OS reuses
        idents and serving threads reuse names ("zoo-serving-batcher"
        across a close()/run() restart), and a reused key would
        resurrect the dead thread's clock — blocking birth-clock
        adoption and minting false pre-start races.  A monotonic
        sequence number keeps every incarnation distinct."""
        t = threading.current_thread()
        tid = getattr(t, self._tid_attr, None)
        if tid is None:
            with self._ledger_lock:
                tid = getattr(t, self._tid_attr, None)
                if tid is None:
                    self._tid_seq += 1
                    tid = f"{t.name}#{self._tid_seq}"
                    setattr(t, self._tid_attr, tid)
        return tid

    def _vc(self, tid: str) -> _VC:
        vc = self._thread_vc.get(tid)
        if vc is None:
            vc = self._thread_vc[tid] = _VC()
            vc.tick(tid)
        return vc

    def _rng(self, tid: str) -> random.Random:
        rng = self._rngs.get(tid)
        if rng is None:
            # per-thread stream: deterministic for a (seed, thread
            # name) pair, no shared RNG lock to mask races with
            rng = self._rngs[tid] = random.Random(
                (self.seed, tid.split("#", 1)[0]).__repr__())
        return rng

    # ------------------------------------------------- fork/join edges
    def _patch_thread_edges(self) -> None:
        san = self
        self._saved_start = threading.Thread.start
        self._saved_join = threading.Thread.join
        saved_start, saved_join = self._saved_start, self._saved_join

        def start(thread, *a, **kw):          # type: ignore[no-redef]
            parent = san._tid()
            with san._ledger_lock:
                pvc = san._vc(parent)
                # fresh incarnation key + the parent's clock snapshot:
                # the child adopts both on its first ledger touch
                san._tid_seq += 1
                setattr(thread, san._tid_attr,
                        f"{thread.name}#{san._tid_seq}")
                setattr(thread, san._birth_attr, pvc.copy())
                pvc.tick(parent)
            return saved_start(thread, *a, **kw)

        def join(thread, *a, **kw):           # type: ignore[no-redef]
            out = saved_join(thread, *a, **kw)
            if not thread.is_alive():
                me = san._tid()
                dead = getattr(thread, san._tid_attr, None)
                with san._ledger_lock:
                    dvc = dead and san._thread_vc.get(dead)
                    if dvc:
                        san._vc(me).join(dvc)
            return out

        threading.Thread.start = start
        threading.Thread.join = join

    def _adopt_birth_vc(self, tid: str) -> None:
        """First ledger touch on a thread: inherit the clock snapshot
        its ``start()`` recorded (the fork edge)."""
        if tid in self._thread_vc:
            return
        vc = self._vc(tid)
        birth = getattr(threading.current_thread(),
                        self._birth_attr, None)
        if birth is not None:
            vc.join(birth)

    # ------------------------------------------------- lock HB edges
    def _profile(self, frame, event, arg):
        if event not in ("c_call", "c_return"):
            return
        name = getattr(arg, "__name__", "")
        if name in _ACQUIRE_NAMES:
            on_return = event == "c_return"
        elif name in _RELEASE_NAMES:
            on_return = False
            if event != "c_call":
                return
        else:
            return
        obj = getattr(arg, "__self__", None)
        if obj is None or \
                type(obj).__name__ not in _LOCK_TYPE_NAMES:
            return
        if obj is self._ledger_lock:
            return                        # our own lock: not modeled
        tid = self._tid()
        with self._ledger_lock:
            self._adopt_birth_vc(tid)
            vc = self._vc(tid)
            if name in _ACQUIRE_NAMES:
                if on_return:             # acquisition completed
                    lvc = self._lock_vc.get(id(obj))
                    if lvc is not None:
                        vc.join(lvc)
            else:                         # about to release
                self._lock_vc[id(obj)] = vc.copy()
                vc.tick(tid)

    def _note_lock_read(self, lock: Any) -> None:
        """A watched instance's lock-typed attribute was just read.

        CPython's ``with`` statement emits a c_call profile event for
        ``__exit__`` but NOT for ``__enter__`` (the special-method
        lookup bypasses the profiler), so a raw ``with self.lock:``
        would never get an acquire edge — only Condition/Queue do,
        through their Python-level ``__enter__`` calling the inner
        lock's explicitly.  The attribute READ in the with-header is
        the observable proxy: by the time the thread's NEXT
        instrumented write runs, the acquire has necessarily
        completed, so the lock's clock is joined there (and while we
        hold the lock nobody else can release it, so the join is
        exact for the with-idiom).  A lock read that is never
        followed by an acquire can only over-join — masking, never
        inventing, a race."""
        if not self._armed or lock is self._ledger_lock:
            return
        pend = getattr(self._tls, "pending_locks", None)
        if pend is None:
            pend = self._tls.pending_locks = []
        pend.append(lock)

    # --------------------------------------------------- access checks
    def _on_access(self, obj: Any, attr: str, is_write: bool) -> None:
        if not self._armed or attr.startswith(_SKIP_PREFIXES):
            return
        if getattr(self._tls, "busy", False):
            return
        self._tls.busy = True
        try:
            tid = self._tid()
            if self.chaos and self._rng(tid).random() < 0.25:
                time.sleep(0)             # forced schedule point
            if not is_write:
                return                    # reads: yield only (GIL)
            site = _site(3)
            with self._ledger_lock:
                self._adopt_birth_vc(tid)
                vc = self._vc(tid)
                pend = getattr(self._tls, "pending_locks", None)
                if pend:
                    for lk in pend:       # see _note_lock_read
                        lvc = self._lock_vc.get(id(lk))
                        if lvc is not None:
                            vc.join(lvc)
                    del pend[:]
                key = (id(obj), attr)
                self._cls_of[id(obj)] = type(obj).__name__
                rec = self._records.get(key)
                if rec is None:
                    rec = self._records[key] = _AccessRecord()
                lw = rec.last_write
                if lw is not None and lw[0] != tid and \
                        not vc.covers(lw[0], lw[1]):
                    self._emit(type(obj).__name__, attr,
                               "write-write", lw[0], tid, lw[2], site)
                vc.tick(tid)
                rec.last_write = (tid, vc.c[tid], site)
        finally:
            self._tls.busy = False

    def _emit(self, cls: str, attr: str, kind: str, ta: str, tb: str,
              sa: str, sb: str) -> None:
        v = Violation(cls, attr, kind, ta, tb, sa, sb)
        if v.key() in self._seen or \
                len(self._violations) >= self.max_violations:
            return
        self._seen.add(v.key())
        self._violations.append(v)

    # ------------------------------------------------- class instrumentation
    def _instrument(self, cls: type) -> None:
        san = self
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__

        def __getattribute__(obj, name):      # noqa: N807
            value = orig_get(obj, name)
            if not name.startswith(_SKIP_PREFIXES):
                try:
                    inst = orig_get(obj, "__dict__")
                except AttributeError:        # __slots__ classes
                    inst = None
                # data reads only: methods resolve on the class and
                # carry no shared-state payload themselves
                if inst is None or name in inst:
                    san._on_access(obj, name, is_write=False)
                    if type(value).__name__ in _LOCK_TYPE_NAMES:
                        san._note_lock_read(value)
            return value

        def __setattr__(obj, name, value):    # noqa: N807
            san._on_access(obj, name, is_write=True)
            orig_set(obj, name, value)

        self._patched.append((cls, orig_get, orig_set))
        cls.__getattribute__ = __getattribute__   # type: ignore
        cls.__setattr__ = __setattr__             # type: ignore

    # ------------------------------------------------------------ lifecycle
    def arm(self, watch) -> "Sanitizer":
        if self._armed:
            raise RuntimeError("sanitizer already armed")
        self._armed = True
        for cls in watch:
            self._instrument(cls)
        self._patch_thread_edges()
        if self.switch_interval is not None:
            self._saved_switch = sys.getswitchinterval()
            # seeded perturbation: vary the interval a little per
            # seed so reruns explore different preemption points
            jitter = random.Random(self.seed).uniform(0.5, 1.5)
            sys.setswitchinterval(self.switch_interval * jitter)
        self._saved_profile = sys.getprofile()
        sys.setprofile(self._profile)
        threading.setprofile(self._profile)
        tid = self._tid()       # outside the lock: _tid takes it too
        with self._ledger_lock:
            self._vc(tid)
        return self

    def disarm(self) -> List[Violation]:
        if not self._armed:
            return list(self._violations)
        self._armed = False
        sys.setprofile(self._saved_profile)
        threading.setprofile(None)
        if self._saved_switch is not None:
            sys.setswitchinterval(self._saved_switch)
        if self._saved_start is not None:
            threading.Thread.start = self._saved_start
            threading.Thread.join = self._saved_join
        for cls, orig_get, orig_set in self._patched:
            cls.__getattribute__ = orig_get       # type: ignore
            cls.__setattr__ = orig_set            # type: ignore
        self._patched.clear()
        return list(self._violations)

    @property
    def violations(self) -> List[Violation]:
        return list(self._violations)

    def __enter__(self) -> "Sanitizer":
        return self

    def __exit__(self, *exc) -> None:
        self.disarm()


# ---------------------------------------------------------------- module API
_ACTIVE: Optional[Sanitizer] = None
_SESSION_SEQ = 0


def arm(watch, *, seed: int = 0, chaos: bool = True,
        switch_interval: Optional[float] = 1e-5) -> Sanitizer:
    """Arm a fresh sanitizer over ``watch`` (iterable of classes)."""
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE._armed:
        raise RuntimeError("zoo-racecheck: already armed")
    # arm()/disarm() are controller-thread API by contract (the
    # class-instrumentation swap cannot be raced); guarding the
    # singleton would advertise a concurrency it does not support
    # zoolint: disable=RACE005 — controller-thread API by contract
    _ACTIVE = Sanitizer(seed=seed, chaos=chaos,
                        switch_interval=switch_interval)
    return _ACTIVE.arm(watch)


def disarm() -> List[Violation]:
    global _ACTIVE
    if _ACTIVE is None:
        return []
    out = _ACTIVE.disarm()
    # zoolint: disable=RACE005 — controller-thread API (see arm())
    _ACTIVE = None
    return out


def active() -> Optional[Sanitizer]:
    return _ACTIVE


def violations() -> List[Violation]:
    return [] if _ACTIVE is None else _ACTIVE.violations


# ------------------------------------------------------------- static join
def join_static(viols: List[Violation],
                static_findings: List[Dict]) -> List[Dict]:
    """Label every static RACE016 finding confirmed/unconfirmed
    against the runtime violations, and surface runtime-only races.

    Matching key: the static finding's ``symbol`` is
    ``Class.Qual.attr``; a runtime violation matches when its class
    name equals the symbol's class tail and the attr matches."""
    runtime = {(v.cls, v.attr) for v in viols}
    out: List[Dict] = []
    matched: Set[Tuple[str, str]] = set()
    for f in static_findings:
        if f.get("rule") != "RACE016":
            continue
        sym = f.get("symbol") or ""
        clsq, _, attr = sym.rpartition(".")
        cls_tail = clsq.rpartition(".")[2]
        hit = (cls_tail, attr) in runtime
        if hit:
            matched.add((cls_tail, attr))
        out.append({"label": "confirmed" if hit else "unconfirmed",
                    "symbol": sym, "path": f.get("path"),
                    "line": f.get("line"),
                    "message": f.get("message", "")})
    for v in viols:
        if (v.cls, v.attr) not in matched:
            out.append({"label": "runtime-only",
                        "symbol": f"{v.cls}.{v.attr}",
                        "path": v.site_b.rsplit(":", 1)[0],
                        "line": int(v.site_b.rsplit(":", 1)[1])
                        if v.site_b.rsplit(":", 1)[1].isdigit() else 0,
                        "message": f"{v.kind} between {v.thread_a} "
                                   f"and {v.thread_b}"})
    return out


# --------------------------------------------------------------- fixtures
class _RacyCounter:
    """The CI drill's deliberately racy class: unlocked
    read-modify-write from two threads — the exact RACE016 shape."""

    def __init__(self):
        self.value = 0

    def bump(self, n: int) -> None:
        for _ in range(n):
            self.value = self.value + 1       # unlocked RMW


class _QueueCounter:
    """The clean twin: same workload, values handed to a single
    owner thread through ``queue.Queue`` — must report ZERO."""

    def __init__(self):
        import queue
        self.q = queue.Queue()
        self.value = 0

    def produce(self, n: int) -> None:
        for _ in range(n):
            self.q.put(1)

    def drain(self, expect: int) -> None:
        for _ in range(expect):
            self.value = self.value + self.q.get()


def racy_fixture(seed: int = 0, iters: int = 50) -> List[Violation]:
    """Run the racy drill once under a fresh sanitizer; returns the
    violations (non-empty on EVERY run — detection is happens-before,
    not consequence-sampling)."""
    san = Sanitizer(seed=seed)
    san.arm([_RacyCounter])
    try:
        c = _RacyCounter()
        ts = [threading.Thread(target=c.bump, args=(iters,),
                               name=f"racer-{i}") for i in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        out = san.disarm()
    return out


def clean_fixture(seed: int = 0, iters: int = 50) -> List[Violation]:
    """Run the queue-handoff twin once; must return []."""
    san = Sanitizer(seed=seed)
    san.arm([_QueueCounter])
    try:
        c = _QueueCounter()
        producers = [threading.Thread(target=c.produce, args=(iters,),
                                      name=f"producer-{i}")
                     for i in (0, 1)]
        owner = threading.Thread(target=c.drain, args=(2 * iters,),
                                 name="owner")
        for t in producers + [owner]:
            t.start()
        for t in producers + [owner]:
            t.join()
    finally:
        out = san.disarm()
    return out


def selftest(runs: int = 100, seed: int = 0) -> Tuple[int, int]:
    """(caught, runs) for the racy drill plus a clean-twin assertion
    each round — the deterministic CI drill."""
    caught = 0
    for i in range(runs):
        if racy_fixture(seed=seed + i):
            caught += 1
        leftover = clean_fixture(seed=seed + i)
        if leftover:                          # pragma: no cover
            raise AssertionError(
                f"clean fixture reported {leftover!r}")
    return caught, runs
