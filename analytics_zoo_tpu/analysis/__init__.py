"""zoolint — JAX/TPU-aware static analysis for the platform.

Stdlib-only by contract (``ast`` + ``tokenize``; **never imports
jax**): ``scripts/zoolint`` loads this package by file path so the
full pass runs in milliseconds with no accelerator stack present —
the same offline contract ``scripts/obs_report.py`` keeps.

The correctness-tooling counterpart to the runtime diagnostics layer:
``observability.diagnostics.CompileMonitor`` can only flag recompile
churn *after* you have paid for it; these rules flag the pattern
before the code ever runs.  Since zoolint v2 the pass is
INTERPROCEDURAL (``project.py`` links every analyzed file into a
module graph + call graph, so helper calls no longer hide findings)
and includes the sharding/HBM/deadlock families
(SHARD007/MEM009/LOCK010, ``rules_graph.py``) with the static
comm/HBM cost models in ``comms.py``.  Since v3 it is also
FLOW-SENSITIVE: ``cfg.py`` builds an intraprocedural CFG with
exception edges and a forward typestate engine, powering the
obligation families in ``rules_flow.py`` — DONATE012
(use-after-donate, the CPU-silent/TPU-fatal class), ACK013
(exactly-once record/Request discharge in serving/), RES015
(exception-path resource release: probe slots, manual acquires,
spawned processes/threads).  Rule catalog + workflow:
docs/static-analysis.md (the catalog table in ``analysis/README.md``
is generated from the registry — see ``cli.rule_catalog``).
"""

from analytics_zoo_tpu.analysis.baseline import (
    apply_baseline,
    count_by_key,
    diff_findings,
    load_baseline,
    write_baseline,
)
from analytics_zoo_tpu.analysis.cfg import (
    CFG,
    build_cfg,
    run_forward,
)
from analytics_zoo_tpu.analysis.comms import (
    all_gather_bytes,
    estimate_step_hbm_bytes,
    estimate_train_step_comm_bytes,
    ring_all_reduce_bytes,
)
from analytics_zoo_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    all_rule_classes,
    analyze_paths,
    analyze_source,
    iter_python_files,
    register_rule,
)
from analytics_zoo_tpu.analysis.project import (
    ProjectContext,
    load_project,
    register_project_rule,
)

__all__ = [
    "CFG",
    "build_cfg",
    "run_forward",
    "Finding",
    "ModuleContext",
    "ProjectContext",
    "Rule",
    "all_gather_bytes",
    "all_rule_classes",
    "analyze_paths",
    "analyze_source",
    "estimate_step_hbm_bytes",
    "estimate_train_step_comm_bytes",
    "iter_python_files",
    "load_project",
    "register_project_rule",
    "register_rule",
    "ring_all_reduce_bytes",
    "apply_baseline",
    "count_by_key",
    "diff_findings",
    "load_baseline",
    "write_baseline",
]
