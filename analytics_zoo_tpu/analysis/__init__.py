"""zoolint — JAX/TPU-aware static analysis for the platform.

Stdlib-only by contract (``ast`` + ``tokenize``; **never imports
jax**): ``scripts/zoolint`` loads this package by file path so the
full pass runs in milliseconds with no accelerator stack present —
the same offline contract ``scripts/obs_report.py`` keeps.

The correctness-tooling counterpart to the runtime diagnostics layer:
``observability.diagnostics.CompileMonitor`` can only flag recompile
churn *after* you have paid for it; these rules flag the pattern
before the code ever runs.  Rule catalog + workflow:
docs/static-analysis.md.
"""

from analytics_zoo_tpu.analysis.baseline import (
    apply_baseline,
    count_by_key,
    diff_findings,
    load_baseline,
    write_baseline,
)
from analytics_zoo_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    all_rule_classes,
    analyze_paths,
    analyze_source,
    iter_python_files,
    register_rule,
)

__all__ = [
    "Finding",
    "ModuleContext",
    "Rule",
    "all_rule_classes",
    "analyze_paths",
    "analyze_source",
    "iter_python_files",
    "register_rule",
    "apply_baseline",
    "count_by_key",
    "diff_findings",
    "load_baseline",
    "write_baseline",
]
