"""zoolint v3 — intraprocedural control-flow graph + forward
typestate framework.

PR 5/7's engine walks each function ONCE (or twice for loops) with
ad-hoc branch merging — enough for value-reuse rules (RNG006) but
structurally unable to express *path-sensitive obligation* protocols:
"this probe slot must be released on EVERY outgoing edge, exception
edges included" (the PR 9 breaker leak), "this record must be
discharged exactly once per iteration" (the PR 13 reclaim defect), or
"this buffer is gone after the donating call on SOME path" (the class
CPU tier-1 runs can never fail on, because donation is a no-op
off-TPU).  This module supplies the missing layer:

- :func:`build_cfg` — a statement-granularity CFG over ``ast`` with
  explicit **edge kinds**: ``next`` (fallthrough), ``true``/``false``
  (branches, loop iterate/exhaust), ``exc`` (exception edges).  It
  models ``if``/``for``/``while`` (``else`` clauses included),
  ``try``/``except``/``else``/``finally``, ``with``, ``break``/
  ``continue``/``return``/``raise``.  ``finally`` bodies are
  **duplicated per continuation** (normal, exception, return, break,
  continue), so a state that leaves a ``try`` abnormally flows
  through its own copy of the cleanup — no infeasible
  normal-path-into-raise-exit joins.
- :func:`run_forward` — a worklist fixpoint engine over the CFG for
  monotone forward analyses.  States are ``{key: frozenset}`` maps
  joined by key-wise union; transfer functions may return
  **different out-states per edge kind** (an assignment does not
  rebind on its exception edge; a guard refines its true/false arms).

Exception-edge policy (documented, deliberately asymmetric):

- a statement *can raise* iff it contains a ``Call``, is a
  ``Raise``/``Assert``, or is a ``with`` header (context-manager
  entry) — attribute access / arithmetic raising is ignored
  (precision over recall, the PR 5 contract);
- inside a ``try`` **with handlers**, exception edges go to every
  handler (static type dispatch is not attempted) and nowhere else —
  the escaping path out of such a ``try`` exists only through an
  explicit (re-)``raise`` in a handler.  A ``try``/``finally`` with
  no handlers routes exception edges through the ``finally`` copy to
  the enclosing target (outer handlers, or the function's
  ``raise`` exit).

Stdlib-only; never imports jax (the ``scripts/zoolint`` contract).
"""

from __future__ import annotations

import ast
from collections import deque
from typing import (Callable, Dict, FrozenSet, List, Optional, Sequence,
                    Tuple)

#: edge kinds
NEXT = "next"
TRUE = "true"
FALSE = "false"
EXC = "exc"

#: a dataflow state: key -> frozenset of abstract facts
State = Dict[str, FrozenSet]


class CFGNode:
    """One CFG node: a simple statement, a compound-statement header
    (``if``/``while`` test, ``for`` iterator, ``with`` items), an
    ``except`` handler entry, or a synthetic node (``entry``/``exit``/
    ``raise``/``reraise``)."""

    __slots__ = ("idx", "kind", "stmt", "exprs", "line", "copy")

    def __init__(self, idx: int, kind: str,
                 stmt: Optional[ast.AST] = None,
                 exprs: Sequence[ast.AST] = ()):
        self.idx = idx
        self.kind = kind          # "stmt" | "if" | "while" | "for" |
        #                           "with" | "handler" | "entry" |
        #                           "exit" | "raise" | "reraise"
        self.stmt = stmt
        #: the expression roots evaluated AT this node (what typestate
        #: transfer functions scan for reads/calls) — for a compound
        #: statement this is the header only, never the nested body
        self.exprs = list(exprs)
        self.line = getattr(stmt, "lineno", 0)
        #: >1 when the same source statement appears again as a
        #: duplicated ``finally`` copy (one copy per continuation)
        self.copy = 1

    def label(self) -> str:
        if self.kind in ("entry", "exit", "raise"):
            return self.kind
        name = "reraise" if self.kind == "reraise" else (
            type(self.stmt).__name__ if self.stmt is not None
            else self.kind)
        suffix = f"#{self.copy}" if self.copy > 1 else ""
        return f"{name}@{self.line}{suffix}"


class CFG:
    """The graph: ``nodes`` by index, ``succs[idx] -> [(idx, kind)]``,
    and the three synthetic anchors ``entry``/``exit``/``raise_exit``
    (normal return vs propagating exception leave through different
    exits — obligation rules treat them differently)."""

    def __init__(self) -> None:
        self.nodes: List[CFGNode] = []
        self.succs: Dict[int, List[Tuple[int, str]]] = {}
        self._copies: Dict[Tuple[str, int], int] = {}
        self.entry = self._new("entry").idx
        self.exit = self._new("exit").idx
        self.raise_exit = self._new("raise").idx

    def _new(self, kind: str, stmt: Optional[ast.AST] = None,
             exprs: Sequence[ast.AST] = ()) -> CFGNode:
        node = CFGNode(len(self.nodes), kind, stmt, exprs)
        if stmt is not None:
            key = (kind, id(stmt))
            self._copies[key] = self._copies.get(key, 0) + 1
            node.copy = self._copies[key]
        self.nodes.append(node)
        self.succs[node.idx] = []
        return node

    def add_edge(self, src: int, dst: int, kind: str = NEXT) -> None:
        if (dst, kind) not in self.succs[src]:
            self.succs[src].append((dst, kind))

    def edges(self) -> List[str]:
        """Human-readable sorted edge list — the unit-test witness
        (``'Assign@3 ->exc handler@5'``).  Only edges reachable from
        ``entry`` are listed: a ``finally`` continuation copy no path
        uses (e.g. the normal-completion copy of a body that always
        returns) is construction residue, not semantics."""
        reach = self.reachable()
        out = []
        for src in sorted(reach):
            for dst, kind in self.succs[src]:
                out.append(f"{self.nodes[src].label()} ->{kind} "
                           f"{self.nodes[dst].label()}")
        return sorted(out)

    def reachable(self) -> "set":
        seen = {self.entry}
        stack = [self.entry]
        while stack:
            cur = stack.pop()
            for dst, _k in self.succs[cur]:
                if dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        return seen

    def preds(self) -> Dict[int, List[Tuple[int, str]]]:
        out: Dict[int, List[Tuple[int, str]]] = {
            i: [] for i in range(len(self.nodes))}
        for src, edges in self.succs.items():
            for dst, kind in edges:
                out[dst].append((src, kind))
        return out


def _stmt_can_raise(stmt: ast.AST) -> bool:
    """Can this SIMPLE statement raise?  Calls anywhere inside it (its
    own expressions only — nested defs/classes define, they don't
    run), explicit asserts."""
    if isinstance(stmt, (ast.Assert, ast.Raise)):
        return True
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return any(isinstance(sub, ast.Call)
                   for dec in stmt.decorator_list
                   for sub in ast.walk(dec))
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.Call):
            return True
    return False


def _exprs_can_raise(exprs: Sequence[ast.AST]) -> bool:
    return any(isinstance(sub, ast.Call)
               for e in exprs for sub in ast.walk(e))


class _Env:
    """Continuation record threaded through the recursive builder.
    ``exc()`` yields the exception targets (handler nodes, or the
    raise exit — possibly through a ``finally`` copy); ``ret``/
    ``brk``/``cont`` yield the single target for ``return``/
    ``break``/``continue``.  All are thunks so ``finally`` wrapping
    composes lazily and copies are built only for transfers that
    actually occur."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc: Callable[[], List[int]],
                 ret: Callable[[], int],
                 brk: Optional[Callable[[], int]] = None,
                 cont: Optional[Callable[[], int]] = None):
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # ------------------------------------------------------------ helpers
    def _exc_edges(self, node: CFGNode, env: _Env) -> None:
        for target in env.exc():
            self.cfg.add_edge(node.idx, target, EXC)

    def _seq(self, stmts: Sequence[ast.stmt], env: _Env,
             follow: int) -> int:
        """Wire ``stmts`` so control reaches ``follow`` afterwards;
        returns the entry node index (``follow`` itself when empty).
        Built back-to-front so each statement knows its successor."""
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, env, entry)
        return entry

    # ------------------------------------------------------------ stmts
    def _stmt(self, stmt: ast.stmt, env: _Env, follow: int) -> int:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            node = cfg._new("if", stmt, [stmt.test])
            body = self._seq(stmt.body, env, follow)
            orelse = self._seq(stmt.orelse, env, follow)
            cfg.add_edge(node.idx, body, TRUE)
            cfg.add_edge(node.idx, orelse, FALSE)
            if _exprs_can_raise(node.exprs):
                self._exc_edges(node, env)
            return node.idx

        if isinstance(stmt, ast.While):
            node = cfg._new("while", stmt, [stmt.test])
            # loop exhaustion (test false) runs the else clause;
            # break skips it and lands straight on follow
            orelse = self._seq(stmt.orelse, env, follow)
            body_env = _Env(env.exc, env.ret,
                            brk=lambda: follow,
                            cont=lambda: node.idx)
            body = self._seq(stmt.body, body_env, node.idx)
            cfg.add_edge(node.idx, body, TRUE)
            cfg.add_edge(node.idx, orelse, FALSE)
            if _exprs_can_raise(node.exprs):
                self._exc_edges(node, env)
            return node.idx

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            node = cfg._new("for", stmt, [stmt.iter])
            orelse = self._seq(stmt.orelse, env, follow)
            body_env = _Env(env.exc, env.ret,
                            brk=lambda: follow,
                            cont=lambda: node.idx)
            body = self._seq(stmt.body, body_env, node.idx)
            cfg.add_edge(node.idx, body, TRUE)      # next item bound
            cfg.add_edge(node.idx, orelse, FALSE)   # exhausted
            if _exprs_can_raise(node.exprs):
                self._exc_edges(node, env)
            return node.idx

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            node = cfg._new(
                "with", stmt, [item.context_expr for item in stmt.items])
            body = self._seq(stmt.body, env, follow)
            cfg.add_edge(node.idx, body, NEXT)
            # context-manager entry can raise; body exceptions ride
            # the body statements' own edges (non-suppressing managers
            # assumed — precision over recall)
            self._exc_edges(node, env)
            return node.idx

        if isinstance(stmt, ast.Try):
            return self._try(stmt, env, follow)

        if isinstance(stmt, ast.Return):
            node = cfg._new("stmt", stmt,
                            [stmt.value] if stmt.value else [])
            cfg.add_edge(node.idx, env.ret(), NEXT)
            if _exprs_can_raise(node.exprs):
                self._exc_edges(node, env)
            return node.idx

        if isinstance(stmt, ast.Raise):
            node = cfg._new("stmt", stmt,
                            [e for e in (stmt.exc, stmt.cause) if e])
            self._exc_edges(node, env)   # no normal successor
            return node.idx

        if isinstance(stmt, ast.Break):
            node = cfg._new("stmt", stmt)
            if env.brk is not None:
                cfg.add_edge(node.idx, env.brk(), NEXT)
            return node.idx

        if isinstance(stmt, ast.Continue):
            node = cfg._new("stmt", stmt)
            if env.cont is not None:
                cfg.add_edge(node.idx, env.cont(), NEXT)
            return node.idx

        # simple statement (assign/expr/aug/ann/pass/del/import/defs…)
        node = cfg._new("stmt", stmt, [stmt])
        cfg.add_edge(node.idx, follow, NEXT)
        if _stmt_can_raise(stmt):
            self._exc_edges(node, env)
        return node.idx

    # -------------------------------------------------------------- try
    def _try(self, stmt: ast.Try, env: _Env, follow: int) -> int:
        cfg = self.cfg
        final = stmt.finalbody

        # ---- finally wrapping: every way OUT of the try region runs
        # its own copy of the cleanup, so abnormal and normal leavings
        # never share a path through it
        copies: Dict[Tuple[str, Tuple[int, ...]], int] = {}

        def through_final(kind: str, target: int) -> int:
            if not final:
                return target
            key = (kind, (target,))
            if key not in copies:
                copies[key] = self._seq(final, env, target)
            return copies[key]

        def exc_through_final() -> List[int]:
            outer = env.exc()
            if not final:
                return outer
            key = ("exc", tuple(outer))
            if key not in copies:
                if len(outer) == 1:
                    copies[key] = self._seq(final, env, outer[0])
                else:
                    # one cleanup copy, then the pending exception
                    # re-dispatches to every outer handler
                    rr = cfg._new("reraise", stmt)
                    for t in outer:
                        cfg.add_edge(rr.idx, t, EXC)
                    copies[key] = self._seq(final, env, rr.idx)
            return [copies[key]]

        outer_env = _Env(
            exc_through_final,
            ret=lambda: through_final("ret", env.ret()),
            brk=(None if env.brk is None
                 else lambda: through_final("brk", env.brk())),
            cont=(None if env.cont is None
                  else lambda: through_final("cont", env.cont())))
        normal_follow = through_final("next", follow)

        # ---- handlers: bodies run under the OUTER continuations (a
        # raise inside a handler propagates out, through the finally)
        handler_nodes: List[int] = []
        for h in stmt.handlers:
            h_node = cfg._new("handler", h,
                              [h.type] if h.type is not None else [])
            h_entry = self._seq(h.body, outer_env, normal_follow)
            cfg.add_edge(h_node.idx, h_entry, NEXT)
            handler_nodes.append(h_node.idx)

        # ---- body: exceptions go to the handlers (all of them — no
        # static type dispatch) or, with none, through the finally out
        body_env = _Env(
            (lambda: list(handler_nodes)) if handler_nodes
            else exc_through_final,
            ret=outer_env.ret, brk=outer_env.brk, cont=outer_env.cont)
        # else clause runs after the body completes normally; ITS
        # exceptions are NOT caught by this try's handlers
        orelse_entry = self._seq(stmt.orelse, outer_env, normal_follow)
        return self._seq(stmt.body, body_env, orelse_entry)


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one ``FunctionDef``/``AsyncFunctionDef`` (or any object
    with a statement-list ``body``)."""
    cfg = CFG()
    builder = _Builder(cfg)
    env = _Env(exc=lambda: [cfg.raise_exit], ret=lambda: cfg.exit)
    first = builder._seq(list(fn.body), env, cfg.exit)
    cfg.add_edge(cfg.entry, first, NEXT)
    # copy ordinals count REACHABLE duplicates only (in creation
    # order) — an unused eagerly-built finally continuation must not
    # shift the labels of the copies paths actually take
    reach = cfg.reachable()
    counts: Dict[Tuple[str, int], int] = {}
    for node in cfg.nodes:
        if node.stmt is None or node.idx not in reach:
            continue
        key = (node.kind, id(node.stmt))
        counts[key] = counts.get(key, 0) + 1
        node.copy = counts[key]
    return cfg


# ---------------------------------------------------------------- engine


def join(a: State, b: State) -> State:
    """Key-wise union — the may-analysis join."""
    if not a:
        return dict(b)
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        out[k] = v if cur is None else (cur | v)
    return out


def _covers(a: State, b: State) -> bool:
    """Does ``a`` already contain everything in ``b``?"""
    for k, v in b.items():
        cur = a.get(k)
        if cur is None or not v <= cur:
            return False
    return True


def run_forward(cfg: CFG, initial: State,
                transfer: Callable[[CFGNode, State],
                                   Dict[Optional[str], State]],
                max_steps: int = 100000) -> Dict[int, State]:
    """Worklist fixpoint: returns the joined IN-state per node.

    ``transfer(node, in_state)`` returns out-states keyed by edge
    kind; ``None`` is the default for kinds not listed.  The lattice
    (key-wise frozenset union) is finite and the transfer functions
    the rules use are monotone, so this terminates; ``max_steps`` is
    a safety net, not a tuning knob."""
    in_states: Dict[int, State] = {cfg.entry: dict(initial)}
    work = deque([cfg.entry])
    steps = 0
    while work and steps < max_steps:
        steps += 1
        idx = work.popleft()
        node = cfg.nodes[idx]
        out = transfer(node, in_states.get(idx, {}))
        default = out.get(None, {})
        for dst, kind in cfg.succs[idx]:
            state = out.get(kind, default)
            cur = in_states.get(dst)
            if cur is None:
                in_states[dst] = dict(state)
                work.append(dst)
            elif not _covers(cur, state):
                in_states[dst] = join(cur, state)
                if dst not in work:
                    work.append(dst)
    return in_states
