"""zoolint command line.

Usage (see docs/static-analysis.md for the workflow)::

    zoolint analytics_zoo_tpu scripts examples
    zoolint --jobs 4 analytics_zoo_tpu ...   # parallel rule runs
    zoolint --baseline .zoolint-baseline.json analytics_zoo_tpu ...
    zoolint --json pkg/ > report.json
    zoolint --diff main-report.json pkg/     # PR gate: new findings only
    zoolint --write-baseline .zoolint-baseline.json pkg/
    zoolint --explain-comms --mesh data=8 --param-count 1000000 pkg/
    zoolint --explain-hbm --param-bytes 4000000 pkg/
    zoolint --list-rules

Exit codes (stable — CI depends on them):

====  ==========================================================
0     clean (no findings / none beyond the baseline or diff base)
1     findings (new findings, stale baseline entries, or
      unparseable files)
2     bad invocation / unreadable baseline
====  ==========================================================
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from analytics_zoo_tpu.analysis import baseline as baseline_mod
from analytics_zoo_tpu.analysis.core import (
    Finding, all_rule_classes, analyze_paths)

JSON_VERSION = 1


def _report_json(findings: List[Finding], errors: List[str]) -> dict:
    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "version": JSON_VERSION,
        "tool": "zoolint",
        "total": len(findings),
        "counts": counts,
        "errors": errors,
        "findings": [f.to_json() for f in findings],
    }


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX/TPU-aware static analysis (interprocedural): "
                    "jit purity, host-sync hygiene, recompile safety, "
                    "donation, thread safety, PRNG key reuse, "
                    "sharding specs, HBM live buffers, lock ordering",
        epilog="suppress one line with "
               "'# zoolint: disable=RULE — reason'")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to analyze")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="acknowledged-debt file; findings it covers "
                         "pass, stale entries fail (only-shrink)")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write the current findings as the baseline "
                         "and exit 0")
    ap.add_argument("--diff", metavar="BASE.json", default=None,
                    help="fail only on findings NOT present in a "
                         "previous --json report (PR gate)")
    ap.add_argument("--rules", metavar="ID[,ID...]", default=None,
                    help="run only these rules")
    ap.add_argument("--root", default=".",
                    help="directory paths are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="fan the per-file rule runs over N worker "
                         "processes (fork; the interprocedural pass "
                         "stays serial; output identical to --jobs 1)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--explain-comms", action="store_true",
                    help="report the static collective-bytes-per-step "
                         "estimate for every jitted train step (same "
                         "ring identities as the runtime "
                         "collective_bytes_total counters) and exit")
    ap.add_argument("--explain-hbm", action="store_true",
                    help="report the static per-step peak-HBM "
                         "composition for every jitted train step "
                         "and exit")
    ap.add_argument("--mesh", default=None, metavar="AXIS=N[,..]",
                    help="mesh sizes for the explain reports, e.g. "
                         "data=8,fsdp=2")
    ap.add_argument("--param-count", type=int, default=None,
                    help="model parameter count to price "
                         "--explain-comms with")
    ap.add_argument("--param-bytes", type=int, default=None,
                    help="model parameter bytes to price "
                         "--explain-hbm with")
    ap.add_argument("--grad-dtype", default="float32",
                    help="gradient sync dtype for --explain-comms "
                         "(default float32)")
    return ap


def _explain(args) -> int:
    """The --explain-comms / --explain-hbm report modes: link the
    project, find the jitted train steps, price them with the stdlib
    comm/HBM models (analysis/comms.py)."""
    from analytics_zoo_tpu.analysis import comms
    from analytics_zoo_tpu.analysis import project as project_mod
    try:
        # validate the mesh spec BEFORE the whole-project parse — a
        # typo'd --mesh should fail instantly, not after linking
        mesh = comms.parse_mesh_spec(args.mesh)
    except ValueError as e:
        print(f"zoolint: {e}", file=sys.stderr)
        return 2
    proj, errors = project_mod.load_project(args.paths, root=args.root)
    lines: List[str] = []
    if args.explain_comms:
        lines += comms.render_comm_report(
            proj.train_steps, mesh, args.param_count, args.grad_dtype)
    if args.explain_hbm:
        lines += comms.render_hbm_report(
            proj.train_steps, args.param_bytes)
    for line in lines:
        print(line)
    for e in errors:
        print(f"zoolint: ERROR {e}", file=sys.stderr)
    return 1 if errors else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from analytics_zoo_tpu.analysis.project import (
            project_rule_classes)
        classes = all_rule_classes() + project_rule_classes()
        for cls in sorted(classes, key=lambda c: c.rule_id):
            print(f"{cls.rule_id}  {cls.severity:7s}  {cls.doc}")
        return 0
    if not args.paths:
        print("zoolint: no paths given (try: zoolint "
              "analytics_zoo_tpu scripts examples)", file=sys.stderr)
        return 2
    if args.explain_comms or args.explain_hbm:
        return _explain(args)

    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    findings, errors = analyze_paths(args.paths, root=args.root,
                                     rule_ids=rule_ids,
                                     jobs=max(1, args.jobs))

    if args.write_baseline:
        prev_total = None
        try:
            prev = baseline_mod.load_baseline(args.write_baseline)
            prev_total = prev.get("pre_fix_total")
        except (OSError, ValueError):
            pass
        data = baseline_mod.write_baseline(
            args.write_baseline, findings, pre_fix_total=prev_total)
        print(f"zoolint: baseline written to {args.write_baseline} "
              f"({data['total']} finding(s), pre-fix total "
              f"{data['pre_fix_total']})")
        for e in errors:
            print(f"zoolint: ERROR {e}", file=sys.stderr)
        return 1 if errors else 0

    stale: List[str] = []
    shown = findings
    if args.baseline:
        try:
            base = baseline_mod.load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"zoolint: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2
        shown, stale = baseline_mod.apply_baseline(findings, base)
    elif args.diff:
        try:
            with open(args.diff, encoding="utf-8") as f:
                base_report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"zoolint: cannot read diff base: {e}",
                  file=sys.stderr)
            return 2
        shown = baseline_mod.diff_findings(findings, base_report)

    if args.json:
        report = _report_json(shown, errors)
        if stale:
            report["stale_baseline_entries"] = stale
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in shown:
            print(f.render())
        for s in stale:
            print(f"zoolint: {s}")
        for e in errors:
            print(f"zoolint: ERROR {e}")
        n_err = sum(1 for f in shown if f.severity == "error")
        if shown or stale or errors:
            print(f"zoolint: {len(shown)} finding(s) "
                  f"({n_err} error(s)), {len(stale)} stale baseline "
                  f"entr(y/ies), {len(errors)} unparseable file(s)")
        else:
            print("zoolint: clean")
    return 1 if (shown or stale or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
