"""zoolint command line.

Usage (see docs/static-analysis.md for the workflow)::

    zoolint analytics_zoo_tpu scripts examples
    zoolint --jobs 4 analytics_zoo_tpu ...   # parallel rule runs
    zoolint --changed-only analytics_zoo_tpu ...   # ~1s pre-commit loop
    zoolint --baseline .zoolint-baseline.json analytics_zoo_tpu ...
    zoolint --json pkg/ > report.json
    zoolint --sarif report.sarif pkg/        # SARIF 2.1.0 alongside
    zoolint --diff main-report.json pkg/     # PR gate: new findings only
    zoolint --write-baseline .zoolint-baseline.json pkg/
    zoolint --explain-comms --mesh data=8 --param-count 1000000 pkg/
    zoolint --explain-hbm --param-bytes 4000000 pkg/
    zoolint --list-rules

The ``--help`` epilog and ``analysis/README.md``'s catalog table are
GENERATED from the live rule registry (:func:`rule_catalog`) — a new
rule family can never silently miss the docs again (the PR 7 help
text stopped at COMPILE011 for two releases).

Exit codes (stable — CI depends on them):

====  ==========================================================
0     clean (no findings / none beyond the baseline or diff base)
1     findings (new findings, stale baseline entries, or
      unparseable files)
2     bad invocation / unreadable baseline / not a git work tree
      (--changed-only)
====  ==========================================================
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis import baseline as baseline_mod
from analytics_zoo_tpu.analysis.core import (
    Finding, all_rule_classes, analyze_paths)

JSON_VERSION = 1


# ------------------------------------------------------- rule catalog


def rule_catalog() -> List[Tuple[str, str, str]]:
    """(rule_id, severity, doc) for EVERY registered rule — module
    rules and project rules — sorted by id.  The single source the
    ``--help`` epilog, ``--list-rules``, the SARIF driver metadata
    and ``analysis/README.md``'s table are generated from."""
    from analytics_zoo_tpu.analysis.project import project_rule_classes
    classes = list(all_rule_classes()) + list(project_rule_classes())
    return sorted({(c.rule_id, c.severity, " ".join(c.doc.split()))
                   for c in classes})


def catalog_lines() -> List[str]:
    return [f"{rid}  {severity:7s}  {doc}"
            for rid, severity, doc in rule_catalog()]


def readme_rule_table() -> str:
    """The markdown table embedded in ``analysis/README.md`` (a test
    regenerates it and diffs, so the file cannot drift)."""
    rows = ["| rule | severity | what it catches |",
            "| --- | --- | --- |"]
    for rid, severity, doc in rule_catalog():
        rows.append(f"| {rid} | {severity} | {doc} |")
    return "\n".join(rows)


# -------------------------------------------------------- changed-only


def _is_git_ref(root: str, value: str) -> bool:
    """Does ``value`` resolve to a commit in ``root``'s repository?
    False too when ``root`` is not a git tree (the later
    ``changed_relpaths`` call reports that case loudly)."""
    import subprocess
    proc = subprocess.run(
        ["git", "-C", root, "rev-parse", "--verify", "--quiet",
         f"{value}^{{commit}}"], capture_output=True, text=True)
    return proc.returncode == 0


def changed_relpaths(root: str, ref: str = "HEAD") -> Set[str]:
    """``root``-relative (POSIX) paths changed vs ``ref`` — tracked
    modifications (staged + unstaged) plus untracked files.  Raises
    ``RuntimeError`` when ``root`` is not a git work tree or the ref
    is unknown: a broken fast path must fail loudly, never silently
    lint nothing.

    Path bases differ per git command — ``diff --name-only`` reports
    TOPLEVEL-relative, ``ls-files --others`` reports cwd-relative —
    so both are rebased onto ``root`` explicitly (with ``--root``
    below the git top, naive joining silently matched nothing and
    the fast path linted nothing at all).  Changes outside ``root``
    are dropped: they cannot correspond to an analyzed file."""
    import subprocess

    def run(*args: str) -> str:
        # config-proofing: core.quotePath (default ON) octal-escapes
        # non-ASCII names and diff.relative rebases the output — both
        # would make the rebasing below match nothing and the fast
        # path silently lint nothing
        proc = subprocess.run(
            ["git", "-C", root, "-c", "core.quotePath=off",
             "-c", "diff.relative=false", *args],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed-only: git {args[0]} failed: "
                f"{proc.stderr.strip() or 'not a git work tree?'}")
        return proc.stdout

    toplevel = run("rev-parse", "--show-toplevel").strip()
    root_abs = os.path.abspath(root)
    out: Set[str] = set()
    for base, text in (
            (toplevel, run("diff", "--name-only", ref, "--")),
            (root_abs, run("ls-files", "--others",
                           "--exclude-standard"))):
        for line in text.splitlines():
            if not line.strip():
                continue
            rel = os.path.relpath(os.path.join(base, line.strip()),
                                  root_abs)
            if not rel.startswith(".."):
                out.add(rel.replace(os.sep, "/"))
    return out


def _report_json(findings: List[Finding], errors: List[str]) -> dict:
    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "version": JSON_VERSION,
        "tool": "zoolint",
        "total": len(findings),
        "counts": counts,
        "errors": errors,
        "findings": [f.to_json() for f in findings],
    }


def build_parser() -> argparse.ArgumentParser:
    # description/epilog are GENERATED from the rule registry so the
    # help text tracks the shipped rule set by construction
    catalog = "\n".join("  " + line for line in catalog_lines())
    ap = argparse.ArgumentParser(
        prog="zoolint",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description=f"JAX/TPU-aware static analysis (interprocedural "
                    f"+ flow-sensitive typestate): "
                    f"{len(rule_catalog())} rule families",
        epilog="rules (generated from the registry):\n"
               f"{catalog}\n\n"
               "suppress one line with "
               "'# zoolint: disable=RULE — reason'")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to analyze")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--sarif", metavar="FILE", default=None,
                    help="also write the (post-baseline/diff) "
                         "findings as a SARIF 2.1.0 document")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="GITREF",
                    help="report only on files changed vs a git ref "
                         "(default HEAD, untracked included); the "
                         "whole project is still parsed and linked, "
                         "so changed files see full facts — the "
                         "pre-commit fast loop.  A value naming an "
                         "existing path (and no ref) is treated as a "
                         "swallowed positional path; a value naming "
                         "BOTH fails loudly — disambiguate with "
                         "./path or a qualified ref.  Stale-baseline "
                         "enforcement is skipped (unchanged files "
                         "are not re-checked)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="acknowledged-debt file; findings it covers "
                         "pass, stale entries fail (only-shrink)")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write the current findings as the baseline "
                         "and exit 0")
    ap.add_argument("--diff", metavar="BASE.json", default=None,
                    help="fail only on findings NOT present in a "
                         "previous --json report (PR gate)")
    ap.add_argument("--rules", metavar="ID[,ID...]", default=None,
                    help="run only these rules")
    ap.add_argument("--root", default=".",
                    help="directory paths are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="fan the per-file rule runs over N worker "
                         "processes (fork; the interprocedural pass "
                         "stays serial; output identical to --jobs 1)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--explain-comms", action="store_true",
                    help="report the static collective-bytes-per-step "
                         "estimate for every jitted train step (same "
                         "ring identities as the runtime "
                         "collective_bytes_total counters) and exit")
    ap.add_argument("--explain-hbm", action="store_true",
                    help="report the static per-step peak-HBM "
                         "composition for every jitted train step "
                         "and exit")
    ap.add_argument("--mesh", default=None, metavar="AXIS=N[,..]",
                    help="mesh sizes for the explain reports, e.g. "
                         "data=8,fsdp=2")
    ap.add_argument("--param-count", type=int, default=None,
                    help="model parameter count to price "
                         "--explain-comms with")
    ap.add_argument("--param-bytes", type=int, default=None,
                    help="model parameter bytes to price "
                         "--explain-hbm with")
    ap.add_argument("--grad-dtype", default="float32",
                    help="gradient sync dtype for --explain-comms "
                         "(default float32)")
    return ap


def _write_sarif(path: str, findings: List[Finding],
                 errors: List[str]) -> None:
    from analytics_zoo_tpu.analysis.sarif import sarif_report
    with open(path, "w", encoding="utf-8") as f:
        json.dump(sarif_report(findings, errors), f, indent=2)
        f.write("\n")


def _explain(args) -> int:
    """The --explain-comms / --explain-hbm report modes: link the
    project, find the jitted train steps, price them with the stdlib
    comm/HBM models (analysis/comms.py)."""
    from analytics_zoo_tpu.analysis import comms
    from analytics_zoo_tpu.analysis import project as project_mod
    try:
        # validate the mesh spec BEFORE the whole-project parse — a
        # typo'd --mesh should fail instantly, not after linking
        mesh = comms.parse_mesh_spec(args.mesh)
    except ValueError as e:
        print(f"zoolint: {e}", file=sys.stderr)
        return 2
    proj, errors = project_mod.load_project(args.paths, root=args.root)
    lines: List[str] = []
    if args.explain_comms:
        lines += comms.render_comm_report(
            proj.train_steps, mesh, args.param_count, args.grad_dtype)
    if args.explain_hbm:
        lines += comms.render_hbm_report(
            proj.train_steps, args.param_bytes)
    for line in lines:
        print(line)
    for e in errors:
        print(f"zoolint: ERROR {e}", file=sys.stderr)
    return 1 if errors else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    # argparse's nargs='?' lets a bare --changed-only swallow the
    # FIRST positional path as its GITREF ('zoolint --changed-only
    # analytics_zoo_tpu ...' is the documented form).  A captured
    # value that names an existing path AND is not a ref was a path;
    # a value that is BOTH a valid ref and an existing path is
    # genuinely ambiguous and must fail loudly — silently picking
    # either side lints the wrong thing (prefix the path with ./ or
    # spell the ref as e.g. origin/NAME to disambiguate).  Must run
    # before the no-paths check: the swallowed path may be the ONLY
    # one.
    if args.changed_only not in (None, "HEAD") and \
            os.path.exists(args.changed_only):
        if _is_git_ref(args.root, args.changed_only):
            print(f"zoolint: --changed-only value "
                  f"{args.changed_only!r} names both a git ref and "
                  f"an existing path — disambiguate (./path or a "
                  f"qualified ref)", file=sys.stderr)
            return 2
        args.paths.insert(0, args.changed_only)
        args.changed_only = "HEAD"

    if args.list_rules:
        for line in catalog_lines():
            print(line)
        return 0
    if not args.paths:
        print("zoolint: no paths given (try: zoolint "
              "analytics_zoo_tpu scripts examples)", file=sys.stderr)
        return 2
    if args.explain_comms or args.explain_hbm:
        return _explain(args)

    only: Optional[Set[str]] = None
    if args.changed_only is not None:
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            # the no-changes fast path below must not outrun the
            # missing-target contract: a typo'd CLI target fails the
            # full run and must fail the fast path too — with the
            # same machine-readable outputs the full path produces
            merrs = [f"{p}: no such file or directory"
                     for p in missing]
            if args.sarif:
                _write_sarif(args.sarif, [], merrs)
            if args.json:
                json.dump(_report_json([], merrs), sys.stdout,
                          indent=2)
                sys.stdout.write("\n")
            else:
                for e in merrs:
                    print(f"zoolint: ERROR {e}", file=sys.stderr)
            return 1
        if args.write_baseline:
            # the baseline records the WHOLE tree's acknowledged debt;
            # writing it from a changed-files-only run would silently
            # discard every unchanged file's entry
            print("zoolint: --write-baseline needs a full run "
                  "(drop --changed-only)", file=sys.stderr)
            return 2
        try:
            only = changed_relpaths(args.root, args.changed_only)
        except RuntimeError as e:
            print(f"zoolint: {e}", file=sys.stderr)
            return 2
        if not only:
            # nothing changed — nothing to judge, by definition
            if args.json:
                json.dump(_report_json([], []), sys.stdout, indent=2)
                sys.stdout.write("\n")
            else:
                print("zoolint: clean (no files changed vs "
                      f"{args.changed_only})")
            if args.sarif:
                _write_sarif(args.sarif, [], [])
            return 0

    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    findings, errors = analyze_paths(args.paths, root=args.root,
                                     rule_ids=rule_ids,
                                     jobs=max(1, args.jobs),
                                     only_relpaths=only)
    if only is not None:
        # errors are "<path>: <reason>"; keep unreadable/unparseable
        # reports only for CHANGED files (compared path-for-path —
        # substring matching misfiled 'a.py' onto 'data.py').
        # Missing CLI targets never reach here: they returned rc 1
        # before analyze_paths ran.
        def _changed_error(e: str) -> bool:
            epath = e.split(": ", 1)[0]
            rel = os.path.relpath(epath, args.root).replace(
                os.sep, "/")
            return rel in only
        errors = [e for e in errors if _changed_error(e)]

    if args.write_baseline:
        prev_total = None
        try:
            prev = baseline_mod.load_baseline(args.write_baseline)
            prev_total = prev.get("pre_fix_total")
        except (OSError, ValueError):
            pass
        data = baseline_mod.write_baseline(
            args.write_baseline, findings, pre_fix_total=prev_total)
        print(f"zoolint: baseline written to {args.write_baseline} "
              f"({data['total']} finding(s), pre-fix total "
              f"{data['pre_fix_total']})")
        for e in errors:
            print(f"zoolint: ERROR {e}", file=sys.stderr)
        return 1 if errors else 0

    stale: List[str] = []
    shown = findings
    if args.baseline:
        try:
            base = baseline_mod.load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"zoolint: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2
        shown, stale = baseline_mod.apply_baseline(findings, base)
        if only is not None:
            # unchanged files were not re-analyzed — their baseline
            # entries are unmatched by construction, not fixed
            stale = []
    elif args.diff:
        try:
            with open(args.diff, encoding="utf-8") as f:
                base_report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"zoolint: cannot read diff base: {e}",
                  file=sys.stderr)
            return 2
        shown = baseline_mod.diff_findings(findings, base_report)

    if args.sarif:
        _write_sarif(args.sarif, shown, errors)

    if args.json:
        report = _report_json(shown, errors)
        if stale:
            report["stale_baseline_entries"] = stale
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in shown:
            print(f.render())
        for s in stale:
            print(f"zoolint: {s}")
        for e in errors:
            print(f"zoolint: ERROR {e}")
        n_err = sum(1 for f in shown if f.severity == "error")
        if shown or stale or errors:
            print(f"zoolint: {len(shown)} finding(s) "
                  f"({n_err} error(s)), {len(stale)} stale baseline "
                  f"entr(y/ies), {len(errors)} unparseable file(s)")
        else:
            print("zoolint: clean")
    return 1 if (shown or stale or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
