"""zoolint's built-in rule set — the JAX/TPU failure modes this stack
actually has, one rule per class.  Each docstring states the *why* and
the runtime-diagnostics counterpart (docs/static-analysis.md renders
the full catalog):

=========  ==========================================================
JIT001     impure jitted/traced functions (side effects fire once at
           trace time, then silently never again)
SYNC002    implicit device→host syncs in train/step/predict loops
           (stalls the dispatch pipeline every iteration)
COMPILE003 recompile hazards (jit-in-loop, f-strings on traced
           values, shape-derived Python scalars as traced args) —
           the static twin of diagnostics.CompileMonitor's churn
           warnings
COMPILE011 direct jax.jit/pjit construction in analytics_zoo_tpu/
           outside the compile/ chokepoint — the program silently
           opts out of AOT warm-start + the persistent executable
           cache (use compile.engine_jit)
DONATE004  training steps that thread params/opt-state through jit
           without donate_argnums (double HBM for the update)
RACE005    module-level mutable state written without a lock in
           modules that run WorkerPool/MetricsServer/serving threads
RNG006     PRNG key consumed twice with no split/fold_in between
           (identical "random" numbers, silently)
=========  ==========================================================
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    ModuleContext, Rule, _dotted, register_rule)

# --------------------------------------------------------------- helpers


def _is_none_guard(test: ast.AST, target: str) -> bool:
    """``target is None`` / ``target == None`` / ``not target`` — the
    guard test of the platform's lazy-init idiom (``target`` is the
    dotted form: a bare name or ``self.attr`` chain)."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.ops[0], (ast.Is, ast.Eq)) and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None and \
            _dotted(test.left) == target:
        return True
    return isinstance(test, ast.UnaryOp) and \
        isinstance(test.op, ast.Not) and \
        _dotted(test.operand) == target


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound INSIDE ``fn`` (params + every assignment form), not
    descending into nested functions — the complement is the
    closed-over/global set JIT001 guards."""
    names: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)

    def collect_target(t: ast.AST) -> None:
        # only true BINDINGS: ``x = ...``/``x, y = ...`` bind names,
        # ``x[k] = ...``/``x.a = ...`` mutate an existing object and
        # must not shadow the closed-over name they mutate
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                collect_target(elt)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            continue   # nested scope: its bindings are not ours
        if isinstance(node, ast.Assign):
            for t in node.targets:
                collect_target(t)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            collect_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            collect_target(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            collect_target(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            collect_target(node.target)
        elif isinstance(node, ast.NamedExpr):
            collect_target(node.target)
        elif isinstance(node, ast.ClassDef):
            names.add(node.name)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return names


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute/subscript chain (``a`` for
    ``a.b[0].c``)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


_MUTATING_METHODS = {
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "appendleft", "extendleft",
    "sort", "reverse", "__setitem__",
}


# ================================================================ JIT001


@register_rule
class ImpureJitRule(Rule):
    """Side effects inside jit/trace-compiled functions.

    Why: a jitted function's Python body runs ONCE, at trace time.  A
    ``print``/``time.time``/``random.random`` call inside it fires
    during tracing and never again; mutation of closed-over or global
    state bakes the traced value in forever.  The program then runs
    wrong *silently* — there is no runtime error to catch, which is
    why this is an error-severity static check (the runtime
    counterpart, ``jax.debug.callback``, is the sanctioned escape
    hatch and is exempt).
    """

    rule_id = "JIT001"
    severity = "error"
    doc = ("side effect in a jit/trace-compiled function (fires once "
           "at trace time, never per step)")

    IMPURE_CALLS = {
        "print": "print() inside jit runs at trace time only — use "
                 "jax.debug.print",
        "input": "input() inside jit blocks tracing, never runs per "
                 "step",
        "breakpoint": "breakpoint() inside jit fires at trace time "
                      "only",
        "time.time": "host clock read inside jit is frozen at trace "
                     "time — time outside the jitted call",
        "time.perf_counter": "host clock read inside jit is frozen at "
                             "trace time — time outside the jitted "
                             "call",
        "time.monotonic": "host clock read inside jit is frozen at "
                          "trace time",
        "time.process_time": "host clock read inside jit is frozen at "
                             "trace time",
        "time.sleep": "time.sleep inside jit sleeps once at trace "
                      "time, never per step",
        "datetime.datetime.now": "host clock read inside jit is "
                                 "frozen at trace time",
        "os.urandom": "host entropy inside jit is drawn once at trace "
                      "time — use jax.random",
        "uuid.uuid4": "host entropy inside jit is drawn once at trace "
                      "time",
    }
    #: module prefixes whose every callable is host-RNG (frozen at
    #: trace time — jax.random is the in-jit mechanism)
    IMPURE_PREFIXES = ("random.", "numpy.random.")
    #: calls whose arguments are ALLOWED to do host work (the
    #: sanctioned side-channel out of a traced program)
    CALLBACK_HOSTS = {
        "jax.debug.print", "jax.debug.callback", "jax.pure_callback",
        "jax.experimental.io_callback",
    }

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) not in ctx.traced_functions:
            return
        if self._inside_callback(node, ctx):
            return
        name = ctx.resolve(node.func)
        if name is not None:
            if name in self.IMPURE_CALLS:
                self.report(node, self.IMPURE_CALLS[name])
                return
            for prefix in self.IMPURE_PREFIXES:
                if name.startswith(prefix):
                    self.report(
                        node,
                        f"host RNG '{name}' inside jit is drawn once "
                        f"at trace time — thread a jax.random key "
                        f"instead")
                    return
        # mutating method on closed-over/global state
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_METHODS:
            # ``.update`` is also optax's PURE GradientTransformation
            # method — ``tx.update(grads, state, params)``.  dict's
            # update takes ONE positional mapping; two or more args is
            # the optimizer signature, not a container mutation.
            if node.func.attr == "update" and len(node.args) >= 2:
                return
            base = _base_name(node.func.value)
            if base and base not in _local_bindings(fn) and \
                    base != "self":
                self.report(
                    node,
                    f"jitted function mutates closed-over/global "
                    f"'{base}' via .{node.func.attr}() — the "
                    f"mutation happens at trace time only")

    def visit_Global(self, node: ast.Global, ctx: ModuleContext) -> None:
        self._flag_scope_decl(node, ctx, "global")

    def visit_Nonlocal(self, node: ast.Nonlocal,
                       ctx: ModuleContext) -> None:
        self._flag_scope_decl(node, ctx, "nonlocal")

    def _flag_scope_decl(self, node: ast.AST, ctx: ModuleContext,
                         kind: str) -> None:
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) not in ctx.traced_functions:
            return
        # the lazy-init singleton idiom (``global X; if X is None:
        # X = ctor(); return X``) memoizes HOST state — calling such a
        # getter at trace time is the platform's config-read
        # convention, not a trace-time-only mutation of program state
        flagged = [n for n in node.names
                   if not self._memoized_only(fn, ctx, n)]
        if not flagged:
            return
        names = ", ".join(flagged)
        self.report(
            node,
            f"jitted function declares {kind} '{names}' — writes to "
            f"it happen at trace time only and are invisible to the "
            f"compiled program")

    @staticmethod
    def _memoized_only(fn: ast.AST, ctx: ModuleContext,
                       name: str) -> bool:
        """Every write to ``name`` inside ``fn`` sits under an
        ``if name is None:`` / ``if not name:`` guard (or there is no
        write at all)."""

        def guarded(node: ast.AST) -> bool:
            # the write must sit in the THEN branch of the guard —
            # an ``else:`` write runs exactly when the name is
            # already set, i.e. on every retrace
            prev: ast.AST = node
            cur = ctx.parent(node)
            while cur is not None and cur is not fn:
                if isinstance(cur, ast.If) and \
                        any(child is prev for child in cur.body) and \
                        _is_none_guard(cur.test, name):
                    return True
                prev = cur
                cur = ctx.parent(cur)
            return False

        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name and \
                        not guarded(node):
                    return False
        return True

    def visit_Assign(self, node: ast.Assign, ctx: ModuleContext) -> None:
        self._check_store(node, node.targets, ctx)

    def visit_AugAssign(self, node: ast.AugAssign,
                        ctx: ModuleContext) -> None:
        self._check_store(node, [node.target], ctx)

    def _check_store(self, node: ast.AST, targets: List[ast.AST],
                     ctx: ModuleContext) -> None:
        """Subscript/attribute stores into names not bound locally —
        in-place mutation of captured state under tracing."""
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) not in ctx.traced_functions:
            return
        local = _local_bindings(fn)
        for tgt in targets:
            if not isinstance(tgt, (ast.Subscript, ast.Attribute)):
                continue
            base = _base_name(tgt)
            if base and base not in local and base != "self":
                self.report(
                    node,
                    f"jitted function writes into closed-over/global "
                    f"'{base}' — the store happens at trace time "
                    f"only")

    def _inside_callback(self, node: ast.AST, ctx: ModuleContext) -> bool:
        cur: Optional[ast.AST] = ctx.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, ast.Call) and \
                    ctx.resolve(cur.func) in self.CALLBACK_HOSTS:
                return True
            cur = ctx.parent(cur)
        return False


# =============================================================== SYNC002


@register_rule
class HostSyncRule(Rule):
    """Implicit device→host syncs inside hot loops.

    Why: ``float(loss)`` / ``.item()`` / ``np.asarray(out)`` on a
    device value blocks the host until the device catches up — inside
    a train/step/predict loop that serializes every iteration and
    empties the dispatch pipeline (the reason PR 1's step-latency
    histogram shows dispatch-to-dispatch time: steady-state training
    never waits).  The runtime twin is the ``train_step_time_seconds
    {device}`` attribution: a hot loop dominated by ``host_dispatch``
    usually hides one of these.  Flagged only for values that came out
    of a function call (device results); host scalars are exempt.
    """

    rule_id = "SYNC002"
    severity = "warning"
    doc = ("implicit device→host sync in a train/step/predict loop "
           "(serializes the dispatch pipeline)")

    SCALAR_CASTS = {"float", "int", "bool"}
    ARRAY_PULLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
    #: calls whose results are host values — casting them is fine
    HOST_SOURCES = (
        "time.", "len", "range", "enumerate", "os.", "math.",
        "numpy.", "id", "sorted", "min", "max", "sum", "abs", "round",
        "str", "repr", "perf_counter", "get_config",
        "int", "float", "bool",
        # host metadata, not device arrays
        "jax.devices", "jax.local_devices", "jax.device_count",
        "jax.local_device_count", "jax.process_count",
        "jax.process_index",
    )
    #: method names whose results are host values regardless of the
    #: receiver (string/dict/env plumbing — the interprocedural
    #: hot-loop marks would otherwise taint every config parser)
    HOST_METHODS = {
        "strip", "lstrip", "rstrip", "split", "rsplit", "lower",
        "upper", "format", "join", "decode", "encode", "group",
        "get", "gethostname", "getvalue", "items", "keys", "values",
        "read", "readline",
        # NOT "copy": list/dict.copy() is host plumbing, but
        # jax.Array.copy() preserves device residency — classified by
        # the receiver below instead
    }

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        fn = ctx.enclosing_function(node)
        if not ctx.is_hot_function(fn) or not ctx.in_loop(node):
            return
        # x.item() — the unambiguous device pull
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            self.report(
                node,
                ".item() in a hot loop blocks on the device every "
                "iteration — batch results and pull once outside the "
                "loop")
            return
        name = ctx.resolve(node.func)
        if name in self.SCALAR_CASTS and len(node.args) == 1 and \
                isinstance(node.args[0], ast.Name):
            argname = node.args[0].id
            if self._device_sourced(argname, fn):
                self.report(
                    node,
                    f"{name}({argname}) in a hot loop forces a "
                    f"device→host sync per iteration — accumulate on "
                    f"device (or sync once per epoch) instead")
        elif name in self.ARRAY_PULLS and node.args and \
                isinstance(node.args[0], ast.Name):
            argname = node.args[0].id
            if self._device_sourced(argname, fn):
                self.report(
                    node,
                    f"{name.split('.')[-1]}({argname}) in a hot loop "
                    f"copies device→host every iteration — keep the "
                    f"value on device or move the pull out of the "
                    f"loop")

    def visit_If(self, node: ast.If, ctx: ModuleContext) -> None:
        """Branching on a device value = an implicit sync too."""
        fn = ctx.enclosing_function(node)
        if not ctx.is_hot_function(fn) or not ctx.in_loop(node):
            return
        test = node.test
        if isinstance(test, ast.Name) and \
                self._device_sourced(test.id, fn, jit_only=True):
            self.report(
                node,
                f"branching on device value '{test.id}' in a hot loop "
                f"syncs every iteration — use jax.lax.cond inside the "
                f"step, or branch on a host-side counter")

    def _device_sourced(self, name: str, fn: ast.AST,
                        jit_only: bool = False,
                        _seen: Optional[Set[str]] = None) -> bool:
        """Was ``name`` assigned (anywhere in ``fn``) from a function
        call that plausibly returns device values?  Parameters and
        host-source calls don't count — precision over recall."""
        if _seen is None:
            _seen = set()
        if name in _seen:
            return False   # copy-chain cycle: stay conservative
        _seen.add(name)
        ctx = self._ctx
        assert ctx is not None
        # explicit source-order queue so nested defs/lambdas are
        # genuinely skipped (their locals are a different scope;
        # ast.walk would descend into them) and the FIRST assignment
        # in source order decides — an explicit host init like
        # ``loss = None`` keeps later device rebinds conservative
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        queue: List[ast.AST] = list(body)
        i = 0
        while i < len(queue):
            node = queue[i]
            i += 1
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            queue.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Assign):
                continue
            bound = False
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        bound = True
            if not bound:
                continue
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if not isinstance(value, ast.Call):
                return False   # literal / arithmetic — host
            if isinstance(value.func, ast.Attribute) and \
                    value.func.attr in self.HOST_METHODS:
                return False   # string/dict/env plumbing
            if isinstance(value.func, ast.Attribute) and \
                    value.func.attr == "copy":
                # device-ness passes through .copy(): a jax.Array
                # copy is still on device, a list/dict copy is host
                recv = value.func.value
                if isinstance(recv, ast.Name):
                    return self._device_sourced(recv.id, fn,
                                                jit_only=jit_only,
                                                _seen=_seen)
                return False   # non-name receiver: host default
            vname = ctx.resolve(value.func) or ""
            if jit_only:
                target = _dotted(value.func)
                if target in ctx.jitted_callables:
                    return True
                wrapped = ctx._wrapped_function(value.func, value)
                return wrapped is not None and \
                    id(wrapped) in ctx.traced_functions
            if any(vname == h or vname.startswith(h)
                   for h in self.HOST_SOURCES if h.endswith(".")) or \
                    vname in self.HOST_SOURCES:
                return False
            return True
        return False


# ============================================================ COMPILE003


@register_rule
class RecompileHazardRule(Rule):
    """Recompile churn, caught before the first run.

    Why: every novel (shape, dtype, static-arg value) combination
    seen by a jitted callable triggers a fresh XLA compile — seconds
    to minutes each.  ``diagnostics.CompileMonitor`` flags the churn
    at runtime *after you have paid for it*; this rule flags the three
    patterns that cause it in source: (1) ``jax.jit`` called inside a
    loop (a fresh cache per iteration), (2) f-strings/str() on traced
    values (forces concretization → trace error or silent constant),
    (3) shape-derived Python scalars (``len(x)``, ``x.shape[i]``)
    passed as *traced* args — the repo convention is a numpy scalar
    or ``static_argnums`` (see trainer.train_step_at).
    """

    rule_id = "COMPILE003"
    severity = "warning"
    doc = ("recompile hazard: jit-in-loop, traced-value "
           "stringification, or shape-derived scalar as a traced arg")

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        name = ctx.resolve(node.func)
        if name in ctx.JIT_WRAPPERS:
            if ctx.in_loop(node) and not self._memoized(node, ctx):
                self.report(
                    node,
                    "jax.jit called inside a loop builds a fresh "
                    "compile cache entry per iteration — hoist the "
                    "jit out of the loop")
            return
        # str()/repr()/format() of a traced parameter inside jit
        fn = ctx.enclosing_function(node)
        if fn is not None and id(fn) in ctx.traced_functions and \
                name in ("str", "repr", "format") and node.args and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id in self._params(fn):
            self.report(
                node,
                f"{name}() of traced value "
                f"'{node.args[0].id}' inside jit forces "
                f"concretization (trace error or baked-in constant)")
            return
        # shape-derived scalar passed as a traced arg to a known-jitted
        # callable (assignment- or decorator-defined) with no statics
        # declared
        target = _dotted(node.func)
        if target is None or target not in ctx.jitted_callables:
            return
        if any(kw.arg in ("static_argnums", "static_argnames")
               for kw in ctx.jitted_callables[target]):
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if self._shape_derived(arg, ctx):
                self.report(
                    arg,
                    f"shape-derived Python scalar passed as a traced "
                    f"arg to jitted '{target}' — a new value retraces "
                    f"(pass a numpy scalar, or declare "
                    f"static_argnums)", line=node.lineno)

    def visit_JoinedStr(self, node: ast.JoinedStr,
                        ctx: ModuleContext) -> None:
        fn = ctx.enclosing_function(node)
        if fn is None or id(fn) not in ctx.traced_functions:
            return
        params = self._params(fn)
        for value in node.values:
            if isinstance(value, ast.FormattedValue) and \
                    isinstance(value.value, ast.Name) and \
                    value.value.id in params:
                self.report(
                    node,
                    f"f-string interpolates traced value "
                    f"'{value.value.id}' inside jit — forces "
                    f"concretization; use jax.debug.print for runtime "
                    f"values")
                return

    @staticmethod
    def _memoized(node: ast.Call, ctx: ModuleContext) -> bool:
        """A jit built under an ``if self._step is None: self._step =
        jax.jit(...)`` guard compiles ONCE no matter how hot the
        enclosing code is — the platform's own lazy-build idiom."""
        target: Optional[str] = None
        cur = ctx.parent(node)
        while isinstance(cur, ast.Call):   # monitor.wrap(jax.jit(..))
            cur = ctx.parent(cur)
        if isinstance(cur, ast.Assign) and len(cur.targets) == 1:
            target = _dotted(cur.targets[0])
        if target is None:
            return False
        prev: ast.AST = cur
        guard = ctx.parent(cur)
        while guard is not None and not isinstance(
                guard, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # only the THEN branch of the None-check memoizes; a build
            # in the ``else:`` runs on every pass through the guard
            if isinstance(guard, ast.If) and \
                    any(child is prev for child in guard.body) and \
                    _is_none_guard(guard.test, target):
                return True
            prev = guard
            guard = ctx.parent(guard)
        return False

    @staticmethod
    def _params(fn: ast.AST) -> Set[str]:
        args = getattr(fn, "args", None)
        if args is None:
            return set()
        out = {a.arg for a in
               (args.posonlyargs + args.args + args.kwonlyargs)}
        out.discard("self")
        return out

    @staticmethod
    def _shape_derived(node: ast.AST, ctx: ModuleContext) -> bool:
        if isinstance(node, ast.Call) and \
                ctx.resolve(node.func) == "len":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            return True
        if isinstance(node, ast.Subscript):
            v = node.value
            return isinstance(v, ast.Attribute) and v.attr == "shape"
        return False


# ============================================================ COMPILE011


@register_rule
class EngineChokepointRule(Rule):
    """Every engine-built jit must go through the ``compile/``
    chokepoint.

    Why: ``analytics_zoo_tpu.compile.engine_jit`` is the platform's
    single lowering chokepoint — it is what gives every compiled
    program the AOT fast path, the persistent executable cache (141s
    ResNet-50 cold compile → ~seconds warm deserialize, BENCH_r05),
    the compile-farm write policy, and the cache hit/miss accounting.
    A direct ``jax.jit``/``pjit`` construction silently opts that
    program OUT of all of it: it recompiles in every process forever
    and its cold-start never shows up in the cache counters.  Scoped
    to ``analytics_zoo_tpu/`` (examples/tests/scripts are free to jit
    directly); ``compile/`` itself is the one place allowed to touch
    the raw wrappers.
    """

    rule_id = "COMPILE011"
    severity = "error"
    doc = ("direct jax.jit/pjit construction outside the compile/ "
           "chokepoint — bypasses the AOT path + persistent "
           "executable cache (use engine_jit)")

    SCOPE = "analytics_zoo_tpu/"
    EXEMPT = ("analytics_zoo_tpu/compile/",)

    def _in_scope(self, ctx: ModuleContext) -> bool:
        rp = ctx.relpath
        return rp.startswith(self.SCOPE) and \
            not any(rp.startswith(e) for e in self.EXEMPT)

    def _flag(self, node: ast.AST, name: str) -> None:
        self.report(
            node,
            f"direct {name}(...) bypasses the engine_jit chokepoint — "
            f"this program gets no AOT warm-start, no persistent "
            f"executable cache entry, and no cache accounting; build "
            f"it with analytics_zoo_tpu.compile.engine_jit (same "
            f"static_argnums/donate_argnums/shardings semantics)")

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        if not self._in_scope(ctx):
            return
        name = ctx.resolve(node.func)
        if name in ctx.RAW_JIT_WRAPPERS:
            self._flag(node, name)

    def visit_FunctionDef(self, node: ast.FunctionDef,
                          ctx: ModuleContext) -> None:
        """Decorator forms visit_Call cannot see: bare ``@jax.jit``
        (an Attribute, not a Call) and ``@partial(jax.jit, ...)``
        (jax.jit is an argument, not the callee).  The ``@jax.jit(..)``
        call form is already a Call and reports there."""
        if not self._in_scope(ctx):
            return
        for dec in node.decorator_list:
            dname = ctx.resolve(dec)
            if dname in ctx.RAW_JIT_WRAPPERS:
                self._flag(dec, dname)
            elif isinstance(dec, ast.Call):
                fname = ctx.resolve(dec.func)
                if fname in ("functools.partial", "partial") and \
                        dec.args and \
                        ctx.resolve(dec.args[0]) in ctx.RAW_JIT_WRAPPERS:
                    self._flag(dec, ctx.resolve(dec.args[0]))


# ============================================================= DONATE004


@register_rule
class DonateRule(Rule):
    """Training steps must donate their state buffers.

    Why: a train step maps (params, opt_state, ...) -> (params,
    opt_state, ...).  Without ``donate_argnums`` XLA must keep the
    input AND output trees live simultaneously — double the HBM for
    the largest arrays in the program, which halves the largest model
    that fits.  Detected on the jit callsite of any function that
    threads an optimizer-state parameter through; eval/predict steps
    (no opt state) are exempt by construction.
    """

    rule_id = "DONATE004"
    severity = "warning"
    doc = ("train-step jit without donate_argnums doubles param/"
           "opt-state HBM")

    STATE_PARAMS = {"opt_state", "optimizer_state", "opt_states"}

    def visit_Call(self, node: ast.Call, ctx: ModuleContext) -> None:
        if ctx.resolve(node.func) not in ctx.JIT_WRAPPERS or \
                not node.args:
            return
        if any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in node.keywords):
            return
        self._check_step(node, ctx._wrapped_function(node.args[0], node))

    def visit_FunctionDef(self, node: ast.FunctionDef,
                          ctx: ModuleContext) -> None:
        """The decorator forms: bare ``@jax.jit`` (no kwargs possible
        → can never donate) and ``@partial(jax.jit, ...)`` (donation
        kwargs live on the partial call)."""
        for dec in node.decorator_list:
            if ctx.resolve(dec) in ctx.JIT_WRAPPERS:
                self._check_step(dec, node)
            elif isinstance(dec, ast.Call):
                fname = ctx.resolve(dec.func)
                # @jax.jit(...) call form, and @partial(jax.jit, ...):
                # in both, donation kwargs live on the call
                is_jit = fname in ctx.JIT_WRAPPERS or (
                    fname in ("functools.partial", "partial")
                    and dec.args
                    and ctx.resolve(dec.args[0]) in ctx.JIT_WRAPPERS)
                if is_jit and not any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in dec.keywords):
                    self._check_step(dec, node)

    def _check_step(self, site: ast.AST, fn) -> None:
        args = getattr(fn, "args", None)
        if args is None:
            return
        names = {a.arg for a in
                 (args.posonlyargs + args.args + args.kwonlyargs)}
        hit = names & self.STATE_PARAMS
        if hit:
            self.report(
                site,
                f"jitted step threads '{sorted(hit)[0]}' through "
                f"without donate_argnums — input and output state "
                f"trees stay live together (double HBM for the "
                f"biggest arrays)")


# =============================================================== RACE005


@register_rule
class SharedStateRule(Rule):
    """Unlocked module-level mutable state in thread-running modules.

    Why: ``data.stages.WorkerPool`` threads, ``PrefetchIterator``
    daemons, the ``MetricsServer`` scrape thread and serving's decode
    pool all execute library code concurrently with the main thread.
    A module-level dict/list mutated without a lock from code those
    threads reach is a data race: CPython makes *some* single ops
    atomic, but read-modify-write sequences (``d[k] = d.get(k) + 1``,
    check-then-set) interleave and corrupt silently.  Scoped to
    modules that demonstrably run threads (imports threading /
    concurrent.futures or instantiates the platform's pool/server
    classes) so pure single-threaded registries don't false-positive.
    """

    rule_id = "RACE005"
    severity = "error"
    doc = ("module-level mutable state mutated without a lock in a "
           "thread-running module")

    def check_module(self, ctx: ModuleContext) -> None:
        if not ctx.threaded:
            return
        shared = set(ctx.module_mutables)
        # names rebound via ``global X`` anywhere also count as shared
        # (the None-then-lazy-init singleton pattern)
        global_decls: Set[str] = set()
        for node in ctx.all_nodes:
            if isinstance(node, ast.Global):
                global_decls.update(node.names)
        shared |= global_decls
        if not shared:
            return
        reads = self._read_counts(ctx, shared)
        for node in ctx.all_nodes:
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue   # module-level init runs before threads start
            name, how = self._mutation_of(node, ctx, shared)
            if name is None:
                continue
            # a name only ever touched in one place isn't shared state
            if reads.get(name, 0) < 2:
                continue
            if self._under_lock(node, ctx):
                continue
            self.report(
                node,
                f"module-level mutable '{name}' {how} without holding "
                f"a lock, in a module that runs threads "
                f"({ctx.thread_evidence}) — wrap the access in a "
                f"threading.Lock")

    def _mutation_of(self, node: ast.AST, ctx: ModuleContext,
                     shared: Set[str]) -> Tuple[Optional[str], str]:
        fn = ctx.enclosing_function(node)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                    base = _base_name(tgt)
                    if base in shared and \
                            self._names_module_global(fn, base):
                        return base, "written into"
                elif isinstance(tgt, ast.Name) and tgt.id in shared \
                        and self._declared_global(fn, tgt.id):
                    return tgt.id, "rebound"
        elif isinstance(node, ast.AugAssign):
            base = _base_name(node.target)
            if base in shared:
                if isinstance(node.target, ast.Name):
                    if not self._declared_global(fn, base):
                        return None, ""
                elif not self._names_module_global(fn, base):
                    return None, ""   # local shadow
                return base, "updated in place"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_METHODS:
            base = _base_name(node.func.value)
            if base in shared and \
                    self._names_module_global(fn, base):
                return base, f"mutated via .{node.func.attr}()"
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    base = _base_name(tgt)
                    if base in shared and \
                            self._names_module_global(fn, base):
                        return base, "deleted from"
        return None, ""

    def _names_module_global(self, fn: Optional[ast.AST],
                             name: str) -> bool:
        """Does ``name`` inside ``fn`` refer to the MODULE global?  A
        local binding of the same name shadows it (not a shared-state
        mutation) — unless the function says ``global name``."""
        if fn is None:
            return True
        if self._declared_global(fn, name):
            return True
        return name not in _local_bindings(fn)

    @staticmethod
    def _declared_global(fn: Optional[ast.AST], name: str) -> bool:
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.Global) and name in node.names:
                return True
        return False

    def _read_counts(self, ctx: ModuleContext,
                     shared: Set[str]) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for node in ctx.all_nodes:
            if isinstance(node, ast.Name) and node.id in shared:
                counts[node.id] = counts.get(node.id, 0) + 1
        return counts

    @staticmethod
    def _under_lock(node: ast.AST, ctx: ModuleContext) -> bool:
        """Any enclosing ``with X:`` where X (or its call target)
        names something lock-ish — the pragmatic guard test."""
        cur = ctx.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            if isinstance(cur, ast.With):
                for item in cur.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    name = (_dotted(expr) or "").lower()
                    if "lock" in name or "mutex" in name or \
                            "guard" in name:
                        return True
            cur = ctx.parent(cur)
        return False


# ================================================================ RNG006


@register_rule
class KeyReuseRule(Rule):
    """A PRNG key consumed by two primitives with no split between.

    Why: jax PRNG keys are VALUES, not stateful generators — two
    ``jax.random.normal(key, ...)`` calls with the same key return the
    *identical* numbers.  Dropout masks equal across layers,
    initializations correlated, augmentation repeated: all silent.
    Consumption = passing the key to a sampling primitive or as an
    ``rng=``/``key=`` kwarg; ``split``/``fold_in``/``PRNGKey`` are
    derivations, and rebinding the name re-arms it.  Loop bodies are
    evaluated twice so a consume-in-loop with no rebind inside the
    loop is caught (the second iteration reuses the key).
    """

    rule_id = "RNG006"
    severity = "error"
    doc = ("PRNG key consumed twice without split/fold_in — "
           "identical random numbers, silently")

    DERIVE = {"split", "fold_in", "PRNGKey", "key", "clone",
              "key_data", "wrap_key_data"}

    def check_module(self, ctx: ModuleContext) -> None:
        for fn in ctx.functions:
            if isinstance(fn, ast.Lambda):
                continue
            self._check_function(fn, ctx)

    # -- per-function linear scan with branch-aware merge ---------------
    def _check_function(self, fn: ast.AST, ctx: ModuleContext) -> None:
        consumed: Dict[str, ast.AST] = {}   # key name -> first consumer
        reported: Set[Tuple[int, int]] = set()
        self._scan(fn.body, consumed, reported, ctx, fn)

    def _scan(self, stmts: List[ast.stmt], consumed: Dict[str, ast.AST],
              reported: Set[Tuple[int, int]], ctx: ModuleContext,
              fn: ast.AST,
              break_sink: Optional[Dict[str, ast.AST]] = None) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # nested scopes get their own pass
            if isinstance(stmt, ast.Break):
                # a break path leaves the loop BODY but still reaches
                # the code after the loop — its consumptions flow to
                # the enclosing loop's post-loop state, not the rest
                # of the body
                if break_sink is not None:
                    for k, v in consumed.items():
                        break_sink.setdefault(k, v)
                continue
            if isinstance(stmt, ast.If):
                # the test expression evaluates first, on every path
                self._apply_expr(stmt.test, consumed, reported, ctx)
                # each branch starts from the current state; afterwards
                # a key consumed in EITHER branch counts as consumed
                # (max-merge: one use per executed path is fine) — but
                # a branch that TERMINATES (return/raise/break/
                # continue) never reaches the code after the If, so
                # its consumptions must not poison the fall-through
                # state (``if small: return normal(rng); ...use rng``)
                before = dict(consumed)
                body_state = dict(before)
                self._scan(stmt.body, body_state, reported, ctx, fn,
                           break_sink)
                else_state = dict(before)
                self._scan(stmt.orelse, else_state, reported, ctx, fn,
                           break_sink)
                body_term = self._terminates(stmt.body)
                else_term = self._terminates(stmt.orelse)
                consumed.clear()
                if body_term and not else_term:
                    consumed.update(else_state)
                elif else_term and not body_term:
                    consumed.update(body_state)
                elif body_term and else_term:
                    consumed.update(before)   # code after is dead-ish
                else:
                    consumed.update(body_state)
                    for k, v in else_state.items():
                        consumed.setdefault(k, v)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                # iterable evaluates ONCE, before the loop
                self._apply_expr(stmt.iter, consumed, reported, ctx)
                # two passes ≈ two iterations: a consume with no rebind
                # inside the loop body reuses the key on iteration 2;
                # the loop target rebinds fresh per iteration.  Breaks
                # inside the body collect in THIS loop's sink and
                # merge into the post-loop state below.
                sink: Dict[str, ast.AST] = {}
                for _ in range(2):
                    for name in self._bound_names(stmt.target):
                        consumed.pop(name, None)
                    self._scan(stmt.body, consumed, reported, ctx, fn,
                               sink)
                self._scan(stmt.orelse, consumed, reported, ctx, fn,
                           break_sink)
                for k, v in sink.items():
                    consumed.setdefault(k, v)
                continue
            if isinstance(stmt, ast.While):
                sink = {}
                for _ in range(2):   # test re-evaluates per iteration
                    self._apply_expr(stmt.test, consumed, reported, ctx)
                    self._scan(stmt.body, consumed, reported, ctx, fn,
                               sink)
                self._scan(stmt.orelse, consumed, reported, ctx, fn,
                           break_sink)
                for k, v in sink.items():
                    consumed.setdefault(k, v)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._apply_expr(item.context_expr, consumed,
                                     reported, ctx)
                    if item.optional_vars is not None:
                        for name in self._bound_names(
                                item.optional_vars):
                            consumed.pop(name, None)
                self._scan(stmt.body, consumed, reported, ctx, fn,
                           break_sink)
                continue
            if isinstance(stmt, ast.Try):
                self._scan(stmt.body, consumed, reported, ctx, fn,
                           break_sink)
                for h in stmt.handlers:
                    self._scan(h.body, consumed, reported, ctx, fn,
                               break_sink)
                self._scan(stmt.orelse, consumed, reported, ctx, fn,
                           break_sink)
                self._scan(stmt.finalbody, consumed, reported, ctx, fn,
                           break_sink)
                continue
            # expression statement / assignment: consumptions first,
            # then rebinds (RHS evaluates before the LHS binds)
            self._apply_expr(stmt, consumed, reported, ctx)
            for name in self._rebinds(stmt):
                consumed.pop(name, None)

    def _apply_expr(self, node: ast.AST, consumed: Dict[str, ast.AST],
                    reported: Set[Tuple[int, int]],
                    ctx: ModuleContext) -> None:
        """Record/flag the key consumptions inside one expression or
        simple statement."""
        for name, site in self._consumptions(node, ctx):
            if name in consumed:
                pos = (site.lineno, site.col_offset)
                if pos not in reported:
                    reported.add(pos)
                    first = consumed[name]
                    self.report(
                        site,
                        f"PRNG key '{name}' already consumed at "
                        f"line {first.lineno} — split it "
                        f"(jax.random.split) or fold_in a counter "
                        f"before reusing")
            else:
                consumed[name] = site

    @classmethod
    def _terminates(cls, stmts: List[ast.stmt]) -> bool:
        """Does this branch body end on a statement whose path never
        reaches the code AFTER the enclosing If?  Return/raise leave
        the function; break leaves the loop body (its consumptions
        still reach post-loop code — the break sink carries them
        there, they just must not poison the rest of the body).
        A trailing If BOTH of whose arms terminate is itself a
        terminator (``if ...: raise A else: raise B``).
        ``continue`` is NOT a terminator: it re-enters the loop
        header, so a key consumed before a ``continue`` is consumed
        again on the next matching iteration (the two-pass loop scan
        needs the state to survive the merge to see it)."""
        if not stmts:
            return False
        last = stmts[-1]
        if isinstance(last, (ast.Return, ast.Raise, ast.Break)):
            return True
        return isinstance(last, ast.If) and \
            cls._terminates(last.body) and cls._terminates(last.orelse)

    @staticmethod
    def _bound_names(target: ast.AST) -> Set[str]:
        names: Set[str] = set()

        def bind(t: ast.AST) -> None:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    bind(elt)
            elif isinstance(t, ast.Starred):
                bind(t.value)

        bind(target)
        return names

    def _consumptions(self, stmt: ast.stmt, ctx: ModuleContext
                      ) -> List[Tuple[str, ast.AST]]:
        out: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            seen_here: Set[str] = set()
            name = ctx.resolve(node.func)
            if name and name.startswith("jax.random."):
                prim = name.rsplit(".", 1)[1]
                if prim in self.DERIVE:
                    continue
                if node.args and isinstance(node.args[0], ast.Name):
                    out.append((node.args[0].id, node))
                    seen_here.add(node.args[0].id)
            else:
                # rng= is the platform's key-threading kwarg
                # (model.apply(..., rng=k)); ``key=`` is NOT counted —
                # it names dict/sort/protobuf keys far more often than
                # PRNG keys
                for kw in node.keywords:
                    if kw.arg == "rng" and \
                            isinstance(kw.value, ast.Name):
                        out.append((kw.value.id, node))
                        seen_here.add(kw.value.id)
            # interprocedural: the project layer resolved this call to
            # a function that consumes a key parameter — the argument
            # passed there is consumed HERE (one entry per name)
            for nm in ctx.rng_call_consumes.get(
                    (node.lineno, node.col_offset), ()):
                if nm not in seen_here:
                    out.append((nm, node))
                    seen_here.add(nm)
        return out

    @classmethod
    def _rebinds(cls, stmt: ast.stmt) -> Set[str]:
        """Names genuinely REBOUND by ``stmt``.  Only binding targets
        count — ``out[rng] = v`` or ``obj.rng = v`` must not re-arm
        ``rng`` (a subscript index / attribute base is a *read*)."""
        names: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                names |= cls._bound_names(tgt)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            names |= cls._bound_names(stmt.target)
        return names
