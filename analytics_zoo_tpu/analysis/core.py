"""zoolint core — the rule framework and per-module analysis driver.

Static analysis for the JAX/TPU failure modes this platform actually
has (impure jitted functions, hidden host syncs, recompile churn,
unlocked shared state under worker threads, PRNG key reuse).  The
engine is **stdlib-only and never imports jax** — ``scripts/zoolint``
must run in milliseconds on a laptop and inside CI images that have no
accelerator stack, the same contract ``scripts/obs_report.py`` keeps.

Architecture:

- :class:`ModuleContext` parses one file and pre-computes the facts
  every rule needs (import aliases, parent links, enclosing-function
  chains, the set of jit/trace-compiled functions, hot-path functions,
  thread usage, module-level mutable globals, suppression comments).
- :class:`Rule` subclasses register ``visit_<NodeType>`` methods; the
  driver walks each AST **once**, dispatching every node to every
  registered rule (classic pylint-style visitor registration).  Rules
  that need whole-function dataflow (RNG006) implement
  ``check_module`` instead/additionally.
- :class:`Finding` carries a stable :meth:`Finding.key` — path + rule
  + enclosing symbol + normalized source line — so the baseline
  survives unrelated line drift.

Suppressions: ``# zoolint: disable=RULE[,RULE2] — reason`` on the
flagged line, or alone on the line directly above it.  ``disable=all``
silences every rule for that line.  The baseline workflow lives in
``baseline.py``; the CLI in ``cli.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
import sys
import tokenize
from typing import (Any, Dict, Iterable, List, Optional, Sequence, Set,
                    Tuple, Type)

SEVERITIES = ("error", "warning")

# the rule list is comma-separated identifiers; ANY trailing text is
# the free-form reason ("— why", "# why", or plain words all work)
_SUPPRESS_RE = re.compile(
    r"#\s*zoolint:\s*disable\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

# ---------------------------------------------------------------- findings


@dataclasses.dataclass
class Finding:
    """One diagnostic, pointing at a concrete line of a concrete file."""

    rule: str             # "JIT001"
    severity: str         # "error" | "warning"
    path: str             # repo-relative, POSIX separators
    line: int             # 1-based
    col: int              # 0-based
    message: str
    symbol: str = ""      # enclosing function qualname ("" = module)
    snippet: str = ""     # stripped source line (stable-key material)

    def key(self) -> str:
        """Identity that survives unrelated edits: line numbers drift
        whenever code above moves, so the baseline keys on *what* was
        flagged (file, rule, enclosing function, source text) instead
        of *where*.  Identical duplicate lines inside one function are
        counted, not distinguished (see baseline.py)."""
        text = "|".join((self.path, self.rule, self.symbol,
                         self.snippet))
        return hashlib.sha256(text.encode()).hexdigest()[:16]

    def to_json(self) -> Dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "path": self.path, "line": self.line, "col": self.col,
            "message": self.message, "symbol": self.symbol,
            "key": self.key(),
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule} {self.severity}: {self.message}{sym}")


# ------------------------------------------------------------ rule registry


class Rule:
    """Base class; subclasses set ``rule_id``/``severity``/``doc`` and
    implement ``visit_<NodeType>(node, ctx)`` and/or
    ``check_module(ctx)``, reporting via ``self.report(...)``."""

    rule_id: str = ""
    severity: str = "warning"
    doc: str = ""

    def __init__(self) -> None:
        self._findings: List[Finding] = []
        self._ctx: Optional["ModuleContext"] = None

    # -- driver hooks ---------------------------------------------------
    def begin_module(self, ctx: "ModuleContext") -> None:
        self._ctx = ctx

    def check_module(self, ctx: "ModuleContext") -> None:
        """Whole-module pass for rules that need dataflow; default
        no-op (visitor methods usually suffice)."""

    def finish_module(self, ctx: "ModuleContext") -> List[Finding]:
        out, self._findings = self._findings, []
        return out

    # -- reporting ------------------------------------------------------
    def report(self, node: ast.AST, message: str,
               line: Optional[int] = None) -> None:
        ctx = self._ctx
        assert ctx is not None
        lineno = line if line is not None else getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        self._findings.append(Finding(
            rule=self.rule_id, severity=self.severity, path=ctx.relpath,
            line=lineno, col=col, message=message,
            symbol=ctx.qualname_of(node),
            snippet=ctx.line_text(lineno).strip()))


_RULE_CLASSES: List[Type[Rule]] = []


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the default set."""
    assert cls.rule_id and cls.severity in SEVERITIES
    _RULE_CLASSES.append(cls)
    return cls


def all_rule_classes() -> List[Type[Rule]]:
    # rules.py / rules_graph.py / rules_flow.py register on import;
    # imported lazily so ``core`` stays importable standalone
    # (scripts/zoolint file-path loading).  All imports run
    # UNCONDITIONALLY (idempotent via sys.modules) — guarding on
    # ``_RULE_CLASSES`` being empty once silently dropped the
    # rules_graph families whenever rules.py had already been
    # imported through another path (project.py's link pass), i.e. in
    # every fresh CLI process.
    from analytics_zoo_tpu.analysis import rules as _rules  # noqa: F401
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        rules_flow as _rules_flow)
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        rules_graph as _rules_graph)
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        rules_race as _rules_race)
    return list(_RULE_CLASSES)


# ------------------------------------------------------- module context


def _fn_name(node: ast.AST) -> str:
    """Display/qualname segment for a function node; lambdas are
    disambiguated by line number ('<lambda:12>') so same-scope
    siblings never share a qualname."""
    return getattr(node, "name", None) or f"<lambda:{node.lineno}>"


def donated_positions(kws) -> Optional[Set[int]]:
    """The literal ``donate_argnums`` of a jit keyword spec as a
    position set; empty set = no donation declared.  ``None`` =
    donation declared in a form that can't be mapped to call-site
    positions (``donate_argnames``, a non-literal argnums expression)
    — callers exempt rather than guess: mere PRESENCE of donation
    must not pass a call whose rebound state args aren't the donated
    ones."""
    donated: Set[int] = set()
    for kw in kws:
        if kw.arg == "donate_argnames":
            return None
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            donated.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, int) for e in v.elts):
            donated |= {e.value for e in v.elts}
        else:
            return None
    return donated


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleContext:
    """Parsed file + the shared facts rules consume.

    The pre-pass performs the *semantic* work once (alias resolution,
    jit-function discovery, scope chains) so individual rules stay
    small pattern matchers.
    """

    #: the RAW jax compile entry points — what COMPILE011 forbids
    #: outside the ``analytics_zoo_tpu/compile/`` chokepoint
    RAW_JIT_WRAPPERS = {
        "jax.jit", "jit", "pjit", "jax.pjit",
        "jax.experimental.pjit.pjit",
    }
    #: callables whose function argument is jit-COMPILED: the raw jax
    #: forms plus the platform chokepoint (``engine_jit`` builds a jit
    #: with identical call semantics, so the purity/donation/recompile
    #: rules keep their coverage over converted sites)
    JIT_WRAPPERS = RAW_JIT_WRAPPERS | {
        "engine_jit", "compile.engine_jit",
        "analytics_zoo_tpu.compile.engine_jit",
        "analytics_zoo_tpu.compile.engine.engine_jit",
    }
    #: callables whose function argument is TRACED (purity contract
    #: identical to jit even when the wrapper itself isn't jit)
    TRACE_WRAPPERS = JIT_WRAPPERS | {
        "jax.grad", "jax.value_and_grad", "jax.vmap", "jax.pmap",
        "jax.checkpoint", "jax.remat", "jax.lax.scan",
        "jax.lax.cond", "jax.lax.while_loop", "jax.lax.fori_loop",
        "jax.lax.map", "jax.custom_vjp", "jax.custom_jvp",
    }
    #: function-name pattern for host-side hot paths (train/step/
    #: predict loops) — SYNC002's scope
    HOT_NAME_RE = re.compile(
        r"(?:^|_)(train|step|predict|fit|epoch|serve|dispatch)")

    def __init__(self, path: str, source: str, root: str = "."):
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[int, ast.AST] = {}
        self._func_of: Dict[int, Optional[ast.AST]] = {}
        self._qualnames: Dict[int, str] = {}
        self._class_qualnames: Dict[int, str] = {}
        self.aliases: Dict[str, str] = {}
        self.functions: List[ast.AST] = []   # FunctionDef/Lambda, all
        self.jit_functions: Set[int] = set()     # id(node), compiled
        self.traced_functions: Set[int] = set()  # id(node), traced
        #: dotted callee name -> the keywords of its jit wrapping:
        #: ``self._step = jax.jit(f, ...)`` / ``g = jax.jit(f)`` /
        #: ``@jax.jit`` / ``@partial(jax.jit, ...)`` — so call sites
        #: of compiled callables are recognizable and their
        #: static_argnums declarations visible (COMPILE003)
        self.jitted_callables: Dict[str, List[ast.keyword]] = {}
        self.threaded = False
        self.thread_evidence = ""
        self.module_mutables: Dict[str, int] = {}   # name -> def lineno
        # ---- interprocedural marks (set by project.py / apply_facts)
        #: function ids whose ENTIRE body behaves as the body of a hot
        #: loop (the function is called from inside a train/step/
        #: predict loop) — in_loop()/is_hot_function() honor this
        self.hot_loop_functions: Set[int] = set()
        #: id(node) -> human-readable reason a mark was applied
        #: ("called from jitted DistributedTrainer._step_core")
        self.mark_reason: Dict[int, str] = {}
        #: call site (lineno, col) -> key-argument names consumed by
        #: the (interprocedurally resolved) callee — RNG006 input
        self.rng_call_consumes: Dict[Tuple[int, int], List[str]] = {}
        #: mesh axis names known to the project (SHARD007); None means
        #: "derive from this module alone / fall back to canonical"
        self.axis_universe: Optional[Set[str]] = None
        #: dotted constant name -> axis string it denotes
        #: ("analytics_zoo_tpu.parallel.mesh.DATA_AXIS" -> "data")
        self.axis_constants: Dict[str, str] = {}
        #: qualname -> thread-role set (zoolint v4 fact bundle); every
        #: function not mentioned runs on the implicit "main" role
        self.thread_roles: Dict[str, frozenset] = {}
        #: qualname -> role set for discovered thread ENTRY points
        self.thread_entries: Dict[str, frozenset] = {}
        self._index()
        # the tokenize-based suppression scan is LAZY (see
        # ``suppressed``): only modules that actually report findings
        # pay for it, and under --jobs the cost lands in the workers
        self._suppressed: Optional[Dict[int, Set[str]]] = None
        self._discover_jit()
        self._discover_threads_and_globals()
        #: qualname -> FunctionDef nodes (lambda qualnames may repeat)
        self.functions_by_qualname: Dict[str, List[ast.AST]] = {}
        for fn in self.functions:
            q = self._qualnames.get(id(fn), "")
            if q:
                self.functions_by_qualname.setdefault(q, []).append(fn)

    @property
    def module_name(self) -> str:
        """Dotted module name derived from the repo-relative path
        ('analytics_zoo_tpu/parallel/mesh.py' ->
        'analytics_zoo_tpu.parallel.mesh')."""
        rp = self.relpath
        if rp.endswith(".py"):
            rp = rp[:-3]
        if rp.endswith("/__init__"):
            rp = rp[: -len("/__init__")]
        return rp.replace("/", ".")

    # ------------------------------------------- interprocedural marks
    def force_traced(self, fn: ast.AST, compiled: bool,
                     reason: str = "") -> None:
        """Mark ``fn`` (and everything defined inside it) traced — the
        project layer calls this when ``fn`` is reachable from a
        jitted/traced function in another scope or module."""
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                self.traced_functions.add(id(sub))
                if compiled:
                    self.jit_functions.add(id(sub))
                if reason and id(sub) not in self.mark_reason:
                    self.mark_reason[id(sub)] = reason

    def force_hot_loop(self, fn: ast.AST, reason: str = "") -> None:
        """Mark ``fn`` as called from inside a hot loop: its whole
        body is then treated as loop-resident by SYNC002/MEM009."""
        self.hot_loop_functions.add(id(fn))
        if reason and id(fn) not in self.mark_reason:
            self.mark_reason[id(fn)] = reason

    def add_external_jitted(self, name: str, static_declared: bool,
                            donate_declared: bool,
                            donate_pos: Optional[List[int]] = None
                            ) -> None:
        """Register a jit-compiled callable imported from another
        analyzed module, synthesizing the keyword facts COMPILE003/
        MEM009 read off local jit sites.  ``donate_pos`` carries the
        LITERAL donate_argnums positions when the defining module
        declared them (so MEM009's coverage check works across module
        boundaries); ``None`` with ``donate_declared`` means donation
        in an unmappable form (argnames / computed) — assume covered."""
        if name in self.jitted_callables:
            return
        kws: List[ast.keyword] = []
        if static_declared:
            kws.append(ast.keyword(arg="static_argnums",
                                   value=ast.Constant(value=None)))
        if donate_declared:
            if donate_pos is not None:
                val: ast.AST = ast.Tuple(
                    elts=[ast.Constant(value=p) for p in donate_pos],
                    ctx=ast.Load())
            else:
                val = ast.Constant(value=None)
            kws.append(ast.keyword(arg="donate_argnums", value=val))
        self.jitted_callables[name] = kws

    # ---------------------------------------------------------- indexing
    @property
    def suppressed(self) -> Dict[int, Set[str]]:
        if self._suppressed is None:
            self._suppressed = self._scan_suppressions(self.source)
        return self._suppressed

    def _scan_suppressions(self, source: str) -> Dict[int, Set[str]]:
        """line(1-based) -> set of rule ids disabled there.  A
        suppression comment alone on a line also covers the next
        line, so block-style disables read naturally.  On a decorated
        ``def`` the decorator lines and the ``def`` line are ALIASED:
        a suppression on either covers findings reported at any of
        them (rules report decorator-form findings at the decorator
        line but def-scoped ones at the ``def`` line, and authors
        can't be expected to know which)."""
        out: Dict[int, Set[str]] = {}
        import io
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip().upper()
                         for r in m.group(1).split(",") if r.strip()}
                lineno = tok.start[0]
                own_line = tok.string.strip() == \
                    self.lines[lineno - 1].strip() if \
                    lineno <= len(self.lines) else False
                out.setdefault(lineno, set()).update(rules)
                if own_line:   # standalone comment covers the next line
                    out.setdefault(lineno + 1, set()).update(rules)
        except tokenize.TokenizeError:
            pass
        # decorated defs: spread each line's rule set over the whole
        # decorator+def span so "either line" suppresses
        for span in self._decorated_def_spans():
            joint: Set[str] = set()
            for ln in span:
                joint |= out.get(ln, set())
            if joint:
                for ln in span:
                    out.setdefault(ln, set()).update(joint)
        return out

    def _decorated_def_spans(self) -> List[List[int]]:
        """[[decorator lines..., def line], ...] for every decorated
        function/class def in the module."""
        return self._decorated_spans

    def _index(self) -> None:
        """ONE recursive walk collecting everything position-dependent:
        parent links, scope chains, qualnames, import aliases, the
        name-binding index, decorated-def spans.  Per-module cost is
        dominated by tree traversal, so the facts that only need node
        dispatch ride the same pass (this file is on the CI critical
        path — the zoolint gate is the slowest tier-1 subprocess)."""
        stack: List[ast.AST] = []
        self._name_assigns: Dict[str, List[ast.Assign]] = {}
        self._decorated_spans: List[List[int]] = []
        #: every node, pre-order — the full-tree passes (jit
        #: discovery, rule dispatch, project scans, lock registry)
        #: iterate this flat list instead of re-running ``ast.walk``
        #: over the tree; the generic-visit machinery (iter_fields +
        #: a deque) is the single largest cost in the gate profile
        self.all_nodes: List[ast.AST] = []

        def walk(node: ast.AST, parent: Optional[ast.AST]) -> None:
            self.all_nodes.append(node)
            if parent is not None:
                self._parents[id(node)] = parent
            is_func = isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda))
            self._func_of[id(node)] = stack[-1] if stack else None
            if is_func:
                self.functions.append(node)
                # lambdas carry their line so two in one function get
                # DISTINCT qualnames — project facts keyed on the
                # shared 'fn.<lambda>' used to force-trace every
                # sibling lambda when only one was jitted
                outer = [_fn_name(f) for f in stack]
                self._qualnames[id(node)] = \
                    ".".join(outer + [_fn_name(node)])
                stack.append(node)
            elif isinstance(node, ast.ClassDef):
                outer = [_fn_name(f) for f in stack]
                self._class_qualnames[id(node)] = \
                    ".".join(outer + [node.name])
                stack.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.decorator_list:
                self._decorated_spans.append(sorted(
                    {d.lineno for d in node.decorator_list}
                    | {node.lineno}))
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.Lambda, ast.Name, ast.Attribute)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._name_assigns.setdefault(
                            t.id, []).append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
            for child in ast.iter_child_nodes(node):
                walk(child, node)
            if is_func or isinstance(node, ast.ClassDef):
                stack.pop()

        walk(self.tree, None)
        # normalize the two ubiquitous scientific aliases even when the
        # import is conventional (import numpy as np)
        self.aliases.setdefault("np", "numpy")
        self.aliases.setdefault("jnp", "jax.numpy")

    # ---------------------------------------------------------- lookups
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest FunctionDef/Lambda strictly containing ``node``."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self._parents.get(id(cur))
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        """Nearest ClassDef strictly containing ``node``."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self._parents.get(id(cur))
            if isinstance(cur, ast.ClassDef):
                return cur
        return None

    def class_qualname(self, node: ast.ClassDef) -> str:
        return self._class_qualnames.get(id(node), node.name)

    def qualname_of(self, node: ast.AST) -> str:
        fn = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            else self.enclosing_function(node)
        if fn is None:
            return ""
        return self._qualnames.get(id(fn), getattr(fn, "name", ""))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute expression with
        import aliases applied ('np.random.normal' ->
        'numpy.random.normal', 'jrandom.split' -> 'jax.random.split',
        bare 'jit' from ``from jax import jit`` -> 'jax.jit')."""
        name = _dotted(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        full = self.aliases.get(head, head)
        return f"{full}.{rest}" if rest else full

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressed.get(finding.line, set())
        return finding.rule.upper() in rules or "ALL" in rules

    def in_loop(self, node: ast.AST, lexical_only: bool = False) -> bool:
        """Is ``node`` inside a For/While body of its own function
        (loops in *enclosing* functions don't count)?  A function the
        project layer marked hot-loop-resident (called from inside a
        hot loop) counts wholesale unless ``lexical_only``."""
        fn = self.enclosing_function(node)
        if not lexical_only and fn is not None and \
                id(fn) in self.hot_loop_functions:
            return True
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not fn:
            par = self._parents.get(id(cur))
            if isinstance(par, (ast.For, ast.AsyncFor, ast.While)) and \
                    cur is not getattr(par, "iter", None) and \
                    cur is not getattr(par, "test", None):
                return True
            cur = par
        return False

    def is_hot_function(self, fn: Optional[ast.AST]) -> bool:
        """Host-side hot path: name matches the train/step/predict
        family, or the project layer proved the function is called
        from inside one (hot_loop_functions).  Jitted functions are
        excluded — host-sync calls there are JIT001/trace errors, not
        hidden syncs."""
        if fn is None or isinstance(fn, ast.Lambda):
            return False
        if id(fn) in self.traced_functions:
            return False
        if id(fn) in self.hot_loop_functions:
            return True
        return bool(self.HOT_NAME_RE.search(fn.name.lower()))

    # ----------------------------------------------- jit-function discovery
    def _local_function_named(self, call: ast.AST,
                              name: str) -> Optional[ast.AST]:
        """The FunctionDef ``name`` visible from ``call``'s scope:
        nearest definition whose enclosing function is an ancestor of
        (or the same as) the call's."""
        chain: List[Optional[ast.AST]] = []
        cur = self.enclosing_function(call)
        while True:
            chain.append(cur)
            if cur is None:
                break
            cur = self.enclosing_function(cur)
        best: Optional[ast.AST] = None
        best_depth = -1
        for fn in self.functions:
            if getattr(fn, "name", None) != name:
                continue
            owner = self.enclosing_function(fn)
            if owner in chain:
                depth = len(chain) - chain.index(owner)
                if depth > best_depth:
                    best, best_depth = fn, depth
        return best

    def _wrapped_function(self, arg: ast.AST,
                          origin: ast.AST) -> Optional[ast.AST]:
        """Resolve the function object an expression denotes: a Lambda
        inline, a Name bound to a local def, or a functools.partial
        of either."""
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            fn = self._local_function_named(origin, arg.id)
            if fn is not None:
                return fn
            return self._local_lambda_named(origin, arg.id)
        if isinstance(arg, ast.Call) and \
                self.resolve(arg.func) in ("functools.partial", "partial") \
                and arg.args:
            return self._wrapped_function(arg.args[0], origin)
        return None

    def scoped_binding_value(self, origin: ast.AST, name: str,
                             types: Tuple[type, ...]) -> Optional[ast.AST]:
        """The VALUE of the deepest in-scope ``name = <expr>`` binding
        visible from ``origin``, restricted to value nodes of
        ``types`` — the one binding-chase used for both name-bound
        lambdas (``fn = lambda ...; jax.jit(fn)``) and method refs
        (``fn = self._step_core``)."""
        chain: List[Optional[ast.AST]] = []
        cur = self.enclosing_function(origin)
        while True:
            chain.append(cur)
            if cur is None:
                break
            cur = self.enclosing_function(cur)
        best: Optional[ast.AST] = None
        best_depth = -1
        for node in self._name_assigns.get(name, ()):
            if not isinstance(node.value, types):
                continue
            owner = self.enclosing_function(node)
            if owner in chain:
                depth = len(chain) - chain.index(owner)
                if depth > best_depth:
                    best, best_depth = node.value, depth
        return best

    def _local_lambda_named(self, origin: ast.AST,
                            name: str) -> Optional[ast.Lambda]:
        return self.scoped_binding_value(origin, name, (ast.Lambda,))

    def _discover_jit(self) -> None:
        """One shared walk discovering jit roots AND thread evidence
        (both need the completed alias table, both dispatch on the
        same node types — merged to keep ModuleContext construction
        at two tree passes total)."""
        roots: List[Tuple[ast.AST, bool]] = []   # (fn, compiled?)
        for node in self.all_nodes:
            # f = jax.jit(g) / @jax.jit / @partial(jax.jit, ...)
            if isinstance(node, ast.Call):
                fname = self.resolve(node.func)
                if fname in self.TRACE_WRAPPERS and node.args:
                    compiled = fname in self.JIT_WRAPPERS
                    fn = self._wrapped_function(node.args[0], node)
                    if fn is not None:
                        roots.append((fn, compiled))
                    if compiled:
                        self._record_jitted_target(node)
                elif fname in self.THREAD_NAMES:
                    self.threaded = True
                    self.thread_evidence = f"{fname}(...)"
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] in ("threading",
                                                "concurrent"):
                        self.threaded = True
                        self.thread_evidence = f"import {a.name}"
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.split(".")[0] in ("threading",
                                                  "concurrent"):
                self.threaded = True
                self.thread_evidence = f"from {node.module} import"
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dname = self.resolve(dec)
                    kwargs: List[ast.keyword] = []
                    if dname is None and isinstance(dec, ast.Call):
                        dname = self.resolve(dec.func)
                        kwargs = list(dec.keywords)
                        if dname in ("functools.partial", "partial") \
                                and dec.args:
                            dname = self.resolve(dec.args[0])
                    if dname in self.TRACE_WRAPPERS:
                        roots.append(
                            (node, dname in self.JIT_WRAPPERS))
                        if dname in self.JIT_WRAPPERS:
                            # decorator-compiled functions are callable
                            # by name like assigned jits
                            self.jitted_callables[node.name] = kwargs
        # everything defined INSIDE a traced function is traced too
        for fn, compiled in roots:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    self.traced_functions.add(id(sub))
                    if compiled:
                        self.jit_functions.add(id(sub))

    def _record_jitted_target(self, jit_call: ast.Call) -> None:
        """Remember ``target = jax.jit(...)`` / ``self.x = jax.jit(..)``
        so call sites of the compiled callable are recognizable
        (COMPILE003's static-arg check)."""
        par = self.parent(jit_call)
        # unwrap monitor.wrap("name", jax.jit(...))-style passthroughs
        while isinstance(par, ast.Call):
            par = self.parent(par)
        if isinstance(par, ast.Assign):
            for tgt in par.targets:
                name = _dotted(tgt)
                if name:
                    self.jitted_callables[name] = \
                        list(jit_call.keywords)
        elif isinstance(par, (ast.AnnAssign, ast.AugAssign)) and \
                par.value is not None:
            name = _dotted(par.target)
            if name:
                self.jitted_callables[name] = list(jit_call.keywords)

    # -------------------------------------- threads + module-level globals
    THREAD_IMPORTS = {"threading", "concurrent.futures", "queue"}
    THREAD_NAMES = {
        "threading.Thread", "concurrent.futures.ThreadPoolExecutor",
        "ThreadPoolExecutor",
        # the platform's own thread-running machinery: any module that
        # instantiates these has its code reachable from worker threads
        "analytics_zoo_tpu.data.stages.WorkerPool",
        "analytics_zoo_tpu.data.stages.PrefetchIterator",
        "analytics_zoo_tpu.observability.exporter.MetricsServer",
        "analytics_zoo_tpu.observability.MetricsServer",
        "WorkerPool", "PrefetchIterator", "MetricsServer",
    }

    def _discover_threads_and_globals(self) -> None:
        # thread evidence rides _discover_jit's walk; only the
        # module-level mutable scan remains here
        for stmt in self.tree.body:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None or not self._is_mutable_container(value):
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    self.module_mutables[tgt.id] = stmt.lineno

    def _is_mutable_container(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            fname = self.resolve(node.func) or ""
            return fname.split(".")[-1] in (
                "dict", "list", "set", "deque", "defaultdict",
                "OrderedDict", "Counter")
        # ``X = None`` rebound later via ``global X`` counts as shared
        # state too, but rules detect that from the global-stmt side
        return False

    # ---------------------------------------------- project-fact intake
    def apply_facts(self, facts: Dict) -> None:
        """Apply the picklable per-module fact bundle the project
        layer computed (``project.ProjectContext.compute_facts``) —
        the only channel between the interprocedural pass and the
        per-module rule run, so ``--jobs`` workers can re-parse a file
        and still see the whole-program facts."""
        for qual, (kind, reason) in (facts.get("traced") or {}).items():
            for fn in self.functions_by_qualname.get(qual, []):
                self.force_traced(fn, kind == "jit", reason)
        for qual, reason in (facts.get("hot_loop") or {}).items():
            for fn in self.functions_by_qualname.get(qual, []):
                self.force_hot_loop(fn, reason)
        for name, d in (facts.get("external_jitted") or {}).items():
            pos = d.get("donate_pos")
            self.add_external_jitted(
                name, bool(d.get("static")), bool(d.get("donate")),
                donate_pos=None if pos is None else list(pos))
        for key, names in (facts.get("rng_consumes") or {}).items():
            self.rng_call_consumes[tuple(key)] = list(names)
        axes = facts.get("axes")
        if axes is not None:
            self.axis_universe = set(axes)
        self.axis_constants.update(facts.get("axis_constants") or {})
        # thread-role attribution (zoolint v4): qualname -> role set
        for qual, roles in (facts.get("thread_roles") or {}).items():
            self.thread_roles[qual] = frozenset(roles)
        for qual, roles in (facts.get("thread_entries") or {}).items():
            self.thread_entries[qual] = frozenset(roles)


# --------------------------------------------------------------- driver


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into the python files to analyze: ``*.py``
    plus extensionless scripts with a python shebang (scripts/zoolint
    itself, launchers)."""
    out: List[str] = []
    seen: Set[str] = set()

    def add(p: str) -> None:
        rp = os.path.realpath(p)
        if rp not in seen:
            seen.add(rp)
            out.append(p)

    for p in paths:
        if os.path.isfile(p):
            add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".ipynb_checkpoints"))
            for fname in sorted(filenames):
                full = os.path.join(dirpath, fname)
                if fname.endswith(".py"):
                    add(full)
                elif "." not in fname:
                    try:
                        with open(full, "rb") as f:
                            first = f.readline()
                        if first.startswith(b"#!") and b"python" in first:
                            add(full)
                    except OSError:
                        pass
    return out


def analyze_source(source: str, path: str = "<string>",
                   root: str = ".",
                   rule_ids: Optional[Iterable[str]] = None
                   ) -> List[Finding]:
    """Analyze one source string; the unit tests' entry point.  The
    interprocedural layer links the single module against itself, so
    same-file helper calls (self-methods, name-bound lambdas) resolve
    exactly as they do in a whole-repo run."""
    from analytics_zoo_tpu.analysis import project as project_mod
    ctx = ModuleContext(path, source, root=root)
    proj = project_mod.ProjectContext([ctx])
    ctx.apply_facts(proj.compute_facts().get(ctx.relpath, {}))
    findings = _run_rules(ctx, rule_ids)
    findings.extend(project_mod.project_findings(proj, rule_ids))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def parse_contexts(paths: Sequence[str], root: str = "."
                   ) -> Tuple[List["ModuleContext"], List[str]]:
    """Parse a path set into ModuleContexts, collecting errors instead
    of raising: missing targets must FAIL, not silently shrink
    coverage (a renamed dir or a CI typo would otherwise turn the
    gate into a no-op), and unreadable/unparseable files are files
    the linter cannot vouch for.  Shared by ``analyze_paths`` and the
    explain modes' ``load_project``."""
    errors: List[str] = []
    for p in paths:
        if not os.path.exists(p):
            errors.append(f"{p}: no such file or directory")
    contexts: List[ModuleContext] = []
    for fpath in iter_python_files([p for p in paths
                                    if os.path.exists(p)]):
        try:
            with open(fpath, encoding="utf-8", errors="replace") as f:
                source = f.read()
        except OSError as e:
            errors.append(f"{fpath}: unreadable: {e}")
            continue
        try:
            contexts.append(ModuleContext(fpath, source, root=root))
        except SyntaxError as e:
            errors.append(f"{fpath}: syntax error: {e}")
    return contexts, errors


# ---- ``--jobs`` worker state: populated in the parent immediately
# before the fork-start pool is created, inherited by the children —
# nothing here is pickled (ASTs travel by fork, findings by dataclass)
_JOBS_STATE: Dict[str, Any] = {}


def _jobs_worker(i: int) -> List[Finding]:
    ctx = _JOBS_STATE["contexts"][i]
    return _run_rules(ctx, _JOBS_STATE["rule_ids"])


def _jobs_project_worker(i: int) -> List[Finding]:
    """One project-rule GROUP (see project_rule_groups) in a pool
    worker: the fork-inherited ProjectContext is fully linked, so a
    child can run cross-module rules exactly as the parent would."""
    from analytics_zoo_tpu.analysis import project as project_mod
    out = project_mod.project_findings(_JOBS_STATE["proj"],
                                       _JOBS_STATE["proj_groups"][i])
    only = _JOBS_STATE["only_relpaths"]
    if only is not None:
        out = [f for f in out if f.path in only]
    return out


def analyze_paths(paths: Sequence[str], root: str = ".",
                  rule_ids: Optional[Iterable[str]] = None,
                  jobs: int = 1,
                  only_relpaths: Optional[Set[str]] = None
                  ) -> Tuple[List[Finding], List[str]]:
    """Analyze files/dirs.  Returns (findings, unparseable-file
    errors).  Unparseable files are surfaced, not silently skipped —
    a file the linter cannot read is a file it cannot vouch for.

    Two phases: (1) parse every file and run the interprocedural
    project pass (serial — it needs the whole module graph); (2) run
    the per-module rules, fanned out over ``jobs`` fork-started
    worker processes when ``jobs > 1``.  Output is sorted either way,
    so ``--jobs`` never changes what the gate sees.

    ``only_relpaths`` (the ``--changed-only`` contract) restricts the
    per-module rule runs — and the project-rule findings — to the
    given repo-relative paths, while the parse + interprocedural link
    still covers EVERYTHING: a changed file is judged with the full
    project facts (imported jits, the axis universe, lock kinds), so
    the fast pre-commit loop can never disagree with the full gate
    about a changed file."""
    findings: List[Finding] = []
    contexts, errors = parse_contexts(paths, root=root)

    from analytics_zoo_tpu.analysis import project as project_mod
    proj = project_mod.ProjectContext(contexts)
    facts = proj.compute_facts()
    for ctx in contexts:
        ctx.apply_facts(facts.get(ctx.relpath, {}))

    run_contexts = contexts if only_relpaths is None else \
        [c for c in contexts if c.relpath in only_relpaths]

    def run_project_rules(ids: Optional[Iterable[str]] = None
                          ) -> List[Finding]:
        out = project_mod.project_findings(
            proj, rule_ids if ids is None else ids)
        if only_relpaths is not None:
            out = [f for f in out if f.path in only_relpaths]
        return out

    if jobs > 1 and len(run_contexts) > 1:
        findings.extend(_run_rules_pool(run_contexts, rule_ids, jobs,
                                        overlap=run_project_rules,
                                        proj=proj,
                                        only_relpaths=only_relpaths))
    else:
        for ctx in run_contexts:
            findings.extend(_run_rules(ctx, rule_ids))
        findings.extend(run_project_rules())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def _run_rules_pool(contexts: List[ModuleContext],
                    rule_ids: Optional[Iterable[str]],
                    jobs: int, overlap, proj=None,
                    only_relpaths: Optional[Set[str]] = None
                    ) -> List[Finding]:
    """Fan the per-module rule runs over a fork-start process pool.
    The project-level rules are the wall-clock long pole (the race
    index + lock summaries cost more than the whole fanned-out
    module pass), so they are split by memo-sharing GROUP
    (project_rule_groups): the parent runs the heaviest group as
    ``overlap(ids)`` while the pool runs the remaining groups ahead
    of the module chunks.  Fork (not spawn) is load-bearing:
    children inherit the parent's already-parsed contexts AND its
    stub ``analytics_zoo_tpu`` parent module, so a ``--jobs`` run
    stays jax-free even on images where the real package is
    importable.  Falls back to serial where fork is unavailable
    (non-POSIX)."""
    import multiprocessing

    def serial() -> List[Finding]:
        out = [f for ctx in contexts
               for f in _run_rules(ctx, rule_ids)]
        out.extend(overlap())
        return out

    # forking a parent that already loaded jax (tests importing the
    # engine through the package) risks deadlock — jax spawns threads
    # and os.fork() only clones the calling one.  The production path
    # (scripts/zoolint via the jax-free file loader) never hits this;
    # anywhere else, degrade to serial (same output, by contract).
    if "jax" in sys.modules:
        return serial()
    try:
        mp = multiprocessing.get_context("fork")
    except ValueError:
        return serial()
    n = len(contexts)
    groups: List[List[str]] = []
    if proj is not None:
        from analytics_zoo_tpu.analysis import project as project_mod
        wanted = {r.upper() for r in rule_ids} if rule_ids else None
        groups = [[rid for rid in g
                   if wanted is None or rid in wanted]
                  for g in project_mod.project_rule_groups()]
        groups = [g for g in groups if g]
    _JOBS_STATE["contexts"] = contexts
    _JOBS_STATE["rule_ids"] = list(rule_ids) if rule_ids else None
    _JOBS_STATE["proj"] = proj
    _JOBS_STATE["proj_groups"] = groups[:-1]
    _JOBS_STATE["only_relpaths"] = only_relpaths
    try:
        with mp.Pool(processes=min(jobs, n)) as pool:
            # project groups are queued FIRST — they are the long
            # poles, and a worker that picks up module chunks ahead
            # of one would push the whole run past the serial time
            proj_async = [pool.apply_async(_jobs_project_worker, (i,))
                          for i in range(len(groups) - 1)]
            async_result = pool.map_async(
                _jobs_worker, range(n),
                chunksize=max(1, n // (min(jobs, n) * 2)))
            # parent works too, not just waits: it takes the
            # heaviest group (rules_race sorts last)
            out = list(overlap(groups[-1])) if groups else []
            chunks = async_result.get()
            for a in proj_async:
                out.extend(a.get())
        return out + [f for chunk in chunks for f in chunk]
    finally:
        _JOBS_STATE.clear()


def _run_rules(ctx: ModuleContext,
               rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    wanted = {r.upper() for r in rule_ids} if rule_ids else None
    rules = [cls() for cls in all_rule_classes()
             if wanted is None or cls.rule_id in wanted]
    if not rules:
        return []
    for rule in rules:
        rule.begin_module(ctx)
    # one walk, dispatching to every registered visit_<Type> method
    dispatch: Dict[str, List[Rule]] = {}
    for rule in rules:
        for attr in dir(rule):
            if attr.startswith("visit_"):
                dispatch.setdefault(attr[6:], []).append(rule)
    for node in ctx.all_nodes:
        for rule in dispatch.get(type(node).__name__, ()):
            getattr(rule, f"visit_{type(node).__name__}")(node, ctx)
    findings: List[Finding] = []
    for rule in rules:
        rule.check_module(ctx)
        findings.extend(f for f in rule.finish_module(ctx)
                        if not ctx.is_suppressed(f))
    return findings
