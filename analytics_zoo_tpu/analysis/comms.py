"""Static communication + HBM cost models (stdlib mirror of
``observability/collectives.py``).

SHARD007's ``zoolint --explain-comms`` report and MEM009's
``--explain-hbm`` report price a jitted train step from the sharding
contract alone, using the SAME ring identities PR 4's runtime
counters use — so a static estimate printed here is directly
comparable to the measured ``collective_bytes_total{op}`` counters
(the tier-1 parity test in ``tests/test_zoolint.py`` holds them to
±10%).  The identities are duplicated rather than imported because
this package must never import jax (``observability/collectives.py``
pulls jax for the param-tree walk); the tier-1 test pins the two
implementations together so they cannot drift silently.

All functions are pure host arithmetic over plain ints/floats.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# canonical op labels — MUST match observability/collectives.py so
# static and runtime reports join on the same keys
OP_PSUM_GRADS = "psum_grads"
OP_ALL_GATHER_PARAMS = "all_gather_params"

DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4,
               "float64": 8, "int8": 1, "int32": 4}


def dtype_bytes(dtype_str: str) -> int:
    return DTYPE_BYTES.get(str(dtype_str), 4)


def ring_all_reduce_bytes(payload_bytes: float, n: int) -> float:
    """Per-device link traffic of a ring all-reduce (reduce-scatter +
    all-gather): ``2(n-1)/n`` of the payload."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * float(payload_bytes)


def all_gather_bytes(payload_bytes: float, n: int) -> float:
    """Per-device link traffic of an all-gather of a sharded payload:
    each device receives the ``(n-1)/n`` it doesn't hold."""
    if n <= 1:
        return 0.0
    return (n - 1) / n * float(payload_bytes)


def estimate_train_step_comm_bytes(
        param_count: int, dp: int, fsdp: int = 1,
        grad_sync_dtype: str = "float32",
        param_dtype: str = "float32") -> Dict[str, float]:
    """``{op: bytes_per_step}`` implied by the trainer's sharding
    contract — the static twin of
    ``observability.collectives.estimate_train_step_collectives``:
    gradients psum (ring all-reduce) over the data×fsdp axes in
    ``grad_sync_dtype``; when ``fsdp > 1``, the forward+backward
    all-gathers that rematerialize the fsdp-sharded params."""
    out: Dict[str, float] = {}
    sync = int(dp) * int(fsdp)
    n = int(param_count)
    if sync > 1 and n:
        out[OP_PSUM_GRADS] = ring_all_reduce_bytes(
            n * dtype_bytes(grad_sync_dtype), sync)
    if fsdp > 1 and n:
        out[OP_ALL_GATHER_PARAMS] = 2.0 * all_gather_bytes(
            n * dtype_bytes(param_dtype), fsdp)
    return out


def estimate_step_hbm_bytes(
        param_bytes: int, opt_slots: int = 2,
        batch_bytes: int = 0, donated: bool = True,
        grad_dtype_ratio: float = 1.0) -> Dict[str, float]:
    """Static per-step peak-HBM composition of a jitted train step.

    ``opt_slots`` is the optimizer's per-param state multiplier (adam
    keeps first+second moments → 2; sgd+momentum → 1; plain sgd → 0).
    Without donation XLA keeps the input AND output params/opt-state
    trees live simultaneously — the doubling MEM009/DONATE004 exist
    to catch.  Returns the components plus their ``peak`` sum."""
    p = float(param_bytes)
    opt = p * float(opt_slots)
    grads = p * float(grad_dtype_ratio)
    live_state = (p + opt) if donated else 2.0 * (p + opt)
    out = {
        "params": p,
        "opt_state": opt,
        "grads": grads,
        "batch": float(batch_bytes),
        "undonated_copies": 0.0 if donated else (p + opt),
        "peak": live_state + grads + float(batch_bytes),
    }
    return out


def parse_mesh_spec(spec: Optional[str]) -> Dict[str, int]:
    """``"data=8,fsdp=2"`` -> ``{"data": 8, "fsdp": 2}`` (the
    ``--mesh`` CLI argument)."""
    out: Dict[str, int] = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"--mesh entry '{part}' is not axis=size")
        axis, _, size = part.partition("=")
        out[axis.strip()] = int(size)
    return out


def render_comm_report(train_steps: List[Dict],
                       mesh: Dict[str, int],
                       param_count: Optional[int],
                       grad_sync_dtype: str = "float32") -> List[str]:
    """Human-readable --explain-comms lines: one block per discovered
    jitted train step, symbolic always, priced when ``param_count``
    and a mesh are given."""
    dp = int(mesh.get("data", 1))
    fsdp = int(mesh.get("fsdp", 1))
    sync = dp * fsdp
    lines: List[str] = []
    if not train_steps:
        lines.append("explain-comms: no jitted train steps "
                     "(opt-state-threading jit roots) found")
        return lines
    lines.append(
        f"explain-comms: ring identities over mesh "
        f"data={dp} fsdp={fsdp} (grad sync {grad_sync_dtype}) — "
        f"comparable to runtime collective_bytes_total{{op}} / steps")
    for step in train_steps:
        lines.append(f"{step['path']}:{step['line']}: jitted step "
                     f"[{step['symbol']}]")
        lines.append(
            f"  {OP_PSUM_GRADS}: 2(n-1)/n x grad_bytes, "
            f"n = dpxfsdp = {sync}")
        if fsdp > 1:
            lines.append(
                f"  {OP_ALL_GATHER_PARAMS}: 2 x (n-1)/n x "
                f"param_bytes, n = fsdp = {fsdp} (fwd+bwd regather)")
        else:
            lines.append(f"  {OP_ALL_GATHER_PARAMS}: inactive "
                         f"(fsdp={fsdp})")
        if param_count:
            est = estimate_train_step_comm_bytes(
                param_count, dp, fsdp, grad_sync_dtype)
            for op in sorted(est):
                lines.append(f"  -> {op}: {est[op]:,.0f} bytes/step "
                             f"({param_count:,} params)")
    return lines


def render_hbm_report(train_steps: List[Dict],
                      param_bytes: Optional[int],
                      opt_slots: int = 2,
                      batch_bytes: int = 0) -> List[str]:
    """Human-readable --explain-hbm lines: static per-step peak-bytes
    composition for each discovered jitted train step, with and
    without donation so the DONATE004/MEM009 cost is explicit."""
    lines: List[str] = []
    if not train_steps:
        lines.append("explain-hbm: no jitted train steps "
                     "(opt-state-threading jit roots) found")
        return lines
    lines.append(
        "explain-hbm: peak ~= params + opt_state(+slots) + grads + "
        "batch; +params+opt_state again when not donated — compare "
        "with device_memory_* telemetry gauges")
    for step in train_steps:
        lines.append(f"{step['path']}:{step['line']}: jitted step "
                     f"[{step['symbol']}]")
        if param_bytes:
            don = estimate_step_hbm_bytes(param_bytes, opt_slots,
                                          batch_bytes, donated=True)
            und = estimate_step_hbm_bytes(param_bytes, opt_slots,
                                          batch_bytes, donated=False)
            lines.append(f"  donated:     peak "
                         f"{don['peak']:,.0f} bytes")
            lines.append(f"  not donated: peak "
                         f"{und['peak']:,.0f} bytes "
                         f"(+{und['undonated_copies']:,.0f} dead "
                         f"input copies)")
        else:
            lines.append("  peak = P(1 + opt_slots) + G + B "
                         "(x2 on P+O when not donated) — pass "
                         "--param-bytes to price it")
    return lines
