"""Cluster-wide observability plane: federate per-worker registries
into cluster views, attribute stragglers, and merge per-host traces.

PRs 1 and 3 instrumented ONE process; a 4-host launcher run therefore
produced four disjoint registries and four unsynchronized Chrome
traces.  This module is the fleet half (the BigDL analogue is the
driver-side Metrics table aggregating executor phase timings over the
Spark UI; see BigDL, arXiv:1804.05839):

* **run directory** — the launcher gives every worker
  ``<run_dir>/host-<k>/`` plus a metrics port and a shared clock
  anchor; workers drop ``meta.json`` / ``metrics.jsonl`` /
  ``trace.json`` there (:func:`init_worker_observability`,
  :func:`flush_worker_observability`).
* **federation** — :class:`ClusterAggregator` pulls each worker's
  snapshot (HTTP ``/metrics.json`` while live, JSONL merge offline)
  and merges: counters summed, histograms merged bucket-wise, gauges
  kept as per-host vectors.  Host 0's :class:`MetricsServer` exposes
  the result at ``/metrics/cluster``.
* **attribution** — :func:`straggler_report` answers "which host is
  slow, and is the time compute or collectives": per-host mean step
  wall, barrier-wait share, max−median skew (the straggler), pipeline
  bubble fraction, and the collective byte/time accounting recorded by
  ``observability.collectives``.
* **trace merge** — :func:`merge_traces` aligns per-host Chrome traces
  on the launcher's clock anchor into one cluster timeline
  (``scripts/obs_report.py --merge-hosts``).

IMPORT DISCIPLINE: module level is stdlib-only — no jax, no package
imports — because ``scripts/obs_report.py`` loads this file directly
(``importlib`` by path) to stay runnable on a laptop against artifacts
copied from the pod.  In-process helpers import the package lazily
inside functions.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")

CLUSTER_FILE = "cluster.json"      # written by the launcher
META_FILE = "meta.json"            # written by each worker
METRICS_FILE = "metrics.jsonl"     # registry snapshots, append-only
TRACE_FILE = "trace.json"          # Chrome trace per worker
REQUESTS_FILE = "requests.json"    # request-timeline log per worker

# env contract injected by the launcher (parallel/launcher.py)
ENV_RUN_DIR = "ZOO_TPU_RUN_DIR"
ENV_METRICS_DIR = "ZOO_TPU_METRICS_DIR"
ENV_METRICS_PORT = "ZOO_TPU_METRICS_PORT"
ENV_CLOCK_ANCHOR = "ZOO_TPU_CLOCK_ANCHOR"
ENV_PROCESS_ID = "ZOO_TPU_PROCESS_ID"


def host_dir_name(process_index: int) -> str:
    return f"host-{int(process_index)}"


# ---------------------------------------------------------- key parsing
def parse_series_key(key: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Split a snapshot series key ``name{a="x",b="y"}`` into
    ``(name, ((a, x), (b, y)))``; label-free keys give ``(key, ())``.
    Handles the registry's label-value escaping (\\\\, \\n, \\")."""
    if "{" not in key:
        return key, ()
    name, _, rest = key.partition("{")
    body = rest.rsplit("}", 1)[0]
    pairs: List[Tuple[str, str]] = []
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        lname = body[i:eq]
        # value starts at eq+2 (skip the opening quote)
        j = eq + 2
        val = []
        while j < len(body):
            ch = body[j]
            if ch == "\\" and j + 1 < len(body):
                val.append({"n": "\n"}.get(body[j + 1], body[j + 1]))
                j += 2
                continue
            if ch == '"':
                break
            val.append(ch)
            j += 1
        pairs.append((lname, "".join(val)))
        i = j + 1
        if i < len(body) and body[i] == ",":
            i += 1
    return name, tuple(pairs)


def format_series_key(name: str,
                      pairs: Tuple[Tuple[str, str], ...]) -> str:
    if not pairs:
        return name
    def esc(v: str) -> str:
        return (v.replace("\\", r"\\").replace("\n", r"\n")
                .replace('"', r'\"'))
    body = ",".join(f'{k}="{esc(v)}"' for k, v in pairs)
    return f"{name}{{{body}}}"


def with_label(key: str, label: str, value: str) -> str:
    """Append one label to a series key (skips if already present)."""
    name, pairs = parse_series_key(key)
    if any(k == label for k, _ in pairs):
        return key
    return format_series_key(name, pairs + ((label, str(value)),))


# ------------------------------------------------------- snapshot merge
def _merge_histogram(acc: Dict, h: Dict) -> Dict:
    """Merge one host's histogram summary into the accumulator.
    Bucket-wise when both carry the same ladder (exact merged
    percentiles); degrades to count/sum only on a ladder mismatch."""
    if acc is None:
        return {
            "count": int(h.get("count", 0)),
            "sum": float(h.get("sum", 0.0)),
            "le": list(h.get("le") or []),
            "cum": list(h.get("cum") or []),
        }
    acc["count"] += int(h.get("count", 0))
    acc["sum"] += float(h.get("sum", 0.0))
    if acc.get("le") and acc["le"] == list(h.get("le") or []):
        acc["cum"] = [a + b for a, b in zip(acc["cum"], h["cum"])]
    else:
        acc["le"], acc["cum"] = [], []
    return acc


def _histogram_percentile(le: List[float], cum: List[int],
                          count: int, p: float) -> float:
    """Same convention as _HistogramChild.percentile: the bound of the
    first cumulative bucket covering p% of the count."""
    if count <= 0:
        return 0.0
    target = p / 100.0 * count
    for bound, c in zip(le, cum):
        if c >= target:
            return bound
    return le[-1] if le else 0.0


def merge_snapshots(host_snaps: Dict[str, Dict]) -> Dict:
    """Federate per-host registry snapshots into ONE cluster snapshot:

    * counters — summed across hosts (cluster totals);
    * histograms — merged bucket-wise (count/sum/recomputed p50/p95/p99);
    * gauges — kept as a per-host vector: each series gains a
      ``host`` label (a gauge like queue depth has no meaningful sum).

    ``host_snaps`` maps a host label (e.g. ``"hostname/0"``) to that
    worker's ``MetricsRegistry.snapshot()``.
    """
    out: Dict[str, Dict] = {"counters": {}, "gauges": {},
                            "histograms": {}}
    hists: Dict[str, Optional[Dict]] = {}
    for host in sorted(host_snaps):
        snap = host_snaps[host] or {}
        for key, val in (snap.get("counters") or {}).items():
            out["counters"][key] = out["counters"].get(key, 0.0) \
                + float(val)
        for key, val in (snap.get("gauges") or {}).items():
            out["gauges"][with_label(key, "host", host)] = float(val)
        for key, h in (snap.get("histograms") or {}).items():
            hists[key] = _merge_histogram(hists.get(key), h)
    for key, h in hists.items():
        le, cum, count = h.get("le") or [], h.get("cum") or [], h["count"]
        merged = {"count": count, "sum": round(h["sum"], 6)}
        if le:
            for p in (50, 95, 99):
                merged[f"p{p}"] = _histogram_percentile(le, cum, count, p)
            merged["le"], merged["cum"] = le, cum
        else:   # ladder mismatch across hosts: percentiles undefined
            merged["p50"] = merged["p95"] = merged["p99"] = 0.0
        out["histograms"][key] = merged
    return out


def snapshot_prometheus_text(snap: Dict, prefix_help: str = "") -> str:
    """Render a (merged) snapshot back into Prometheus text exposition
    — what ``/metrics/cluster`` serves.  Histograms keep their bucket
    lines when the merged bucket data survived."""
    lines: List[str] = []
    for key in sorted(snap.get("counters", {})):
        name, _ = parse_series_key(key)
        lines.append(f"{key} {_num(snap['counters'][key])}")
    for key in sorted(snap.get("gauges", {})):
        lines.append(f"{key} {_num(snap['gauges'][key])}")
    for key in sorted(snap.get("histograms", {})):
        h = snap["histograms"][key]
        name, pairs = parse_series_key(key)
        for bound, c in zip(h.get("le") or [], h.get("cum") or []):
            lines.append(
                format_series_key(
                    name + "_bucket",
                    pairs + (("le", _num(bound)),)) + f" {c}")
        if h.get("le"):
            lines.append(
                format_series_key(name + "_bucket",
                                  pairs + (("le", "+Inf"),))
                + f" {h['count']}")
        lines.append(f"{name}_sum"
                     f"{format_series_key('', pairs)} {_num(h['sum'])}"
                     if pairs else f"{name}_sum {_num(h['sum'])}")
        lines.append(f"{name}_count"
                     f"{format_series_key('', pairs)} {h['count']}"
                     if pairs else f"{name}_count {h['count']}")
    return "\n".join(lines) + "\n"


def _num(v) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# --------------------------------------------------------- attribution
def _hist_totals(snap: Dict, metric: str) -> Tuple[int, float, float]:
    """(count, sum, p50) aggregated over every label variant of one
    histogram family in one host's snapshot."""
    count, total, p50s = 0, 0.0, []
    for key, h in (snap.get("histograms") or {}).items():
        name, _ = parse_series_key(key)
        if name != metric or not h.get("count"):
            continue
        count += int(h["count"])
        total += float(h["sum"])
        p50s.append(float(h.get("p50", 0.0)))
    return count, total, max(p50s) if p50s else 0.0


def _gauge_max(snap: Dict, metric: str) -> Optional[float]:
    vals = [float(v) for key, v in (snap.get("gauges") or {}).items()
            if parse_series_key(key)[0] == metric]
    return max(vals) if vals else None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def straggler_report(host_snaps: Dict[str, Dict],
                     step_metric: str = "train_step_latency_seconds",
                     barrier_metric: str = "train_barrier_wait_seconds",
                     skew_threshold: float = 0.10) -> Dict:
    """Cross-host skew and collective attribution.

    Per host: mean/p50 step wall (``step_metric``) and mean barrier
    wait (``barrier_metric`` — the time the FASTER hosts spend parked
    in the sampled cross-host sync, so a near-zero barrier wait plus
    the highest step time is the straggler signature).  Cluster level:
    max−median step-time skew; the max host is named straggler when
    the skew fraction exceeds ``skew_threshold``.  Also surfaces the
    worst pipeline bubble fraction and the summed collective
    byte/second counters (observability/collectives.py).
    """
    per_host: Dict[str, Dict] = {}
    for host, snap in host_snaps.items():
        n, total, p50 = _hist_totals(snap, step_metric)
        bn, btotal, _ = _hist_totals(snap, barrier_metric)
        per_host[host] = {
            "steps": n,
            "mean_step_s": total / n if n else 0.0,
            "p50_step_s": p50,
            "mean_barrier_wait_s": btotal / bn if bn else 0.0,
            "pipeline_bubble_fraction":
                _gauge_max(snap, "pipeline_bubble_fraction"),
        }
    means = {h: d["mean_step_s"] for h, d in per_host.items()
             if d["steps"]}
    report: Dict = {"hosts": sorted(host_snaps), "per_host": per_host,
                    "straggler": None, "skew_seconds": 0.0,
                    "skew_fraction": 0.0}
    if len(means) >= 2:
        med = _median(list(means.values()))
        worst = max(means, key=lambda h: means[h])
        skew = means[worst] - med
        frac = skew / med if med > 0 else 0.0
        report["median_step_s"] = med
        report["skew_seconds"] = skew
        report["skew_fraction"] = frac
        if frac > skew_threshold:
            report["straggler"] = worst
    bubbles = [d["pipeline_bubble_fraction"] for d in per_host.values()
               if d["pipeline_bubble_fraction"] is not None]
    if bubbles:
        report["pipeline_bubble_fraction"] = max(bubbles)
    # collective accounting: cluster-summed bytes/seconds per op
    coll: Dict[str, Dict[str, float]] = {}
    for snap in host_snaps.values():
        for key, val in (snap.get("counters") or {}).items():
            name, pairs = parse_series_key(key)
            if name not in ("collective_bytes_total",
                            "collective_seconds_total"):
                continue
            op = dict(pairs).get("op", "?")
            field = "bytes" if name == "collective_bytes_total" \
                else "seconds"
            coll.setdefault(op, {"bytes": 0.0, "seconds": 0.0})
            coll[op][field] += float(val)
    if coll:
        report["collectives"] = coll
    return report


def cluster_gauges(report: Dict) -> Dict[str, float]:
    """The straggler report distilled into scrapeable gauges — merged
    into the ``/metrics/cluster`` exposition so alerting needs no
    report parsing."""
    out: Dict[str, float] = {
        "cluster_hosts": float(len(report.get("hosts", []))),
        "cluster_step_skew_seconds": float(
            report.get("skew_seconds", 0.0)),
        "cluster_step_skew_fraction": float(
            report.get("skew_fraction", 0.0)),
    }
    if report.get("pipeline_bubble_fraction") is not None:
        out["cluster_pipeline_bubble_fraction"] = float(
            report["pipeline_bubble_fraction"])
    for host in report.get("hosts", []):
        is_straggler = 1.0 if host == report.get("straggler") else 0.0
        out[format_series_key("cluster_is_straggler",
                              (("host", host),))] = is_straggler
    return out


# ------------------------------------------------------------ federation
class WorkerSource:
    """One worker's snapshot source: live HTTP endpoint (preferred)
    with the run-dir JSONL as offline fallback."""

    def __init__(self, name: str, url: Optional[str] = None,
                 path: Optional[str] = None,
                 fetch: Optional[Callable[[], Dict]] = None):
        self.name = name              # host label, e.g. "tpu-a/0"
        self.url = url                # http://host:port (no trailing /)
        self.path = path              # <run_dir>/host-<k>
        self._fetch = fetch           # injectable (tests)

    def snapshot(self, timeout_s: float = 2.0) -> Optional[Dict]:
        if self._fetch is not None:
            try:
                return self._fetch()
            except Exception:
                return None
        if self.url:
            try:
                with urllib.request.urlopen(
                        self.url.rstrip("/") + "/metrics.json",
                        timeout=timeout_s) as resp:
                    return json.loads(resp.read().decode())
            except Exception:
                pass   # fall back to the offline file
        if self.path:
            return _last_jsonl_snapshot(
                os.path.join(self.path, METRICS_FILE))
        return None


def _last_jsonl_snapshot(path: str) -> Optional[Dict]:
    """Latest snapshot record of an append-only registry JSONL.

    Reads from the TAIL (expanding window) and scans lines newest-
    first: the live aggregator calls this per scrape for every worker
    that fell back to its file, and a long run's per-epoch flushes
    grow the file without bound — parsing the whole history per
    scrape would put O(file) work inside the HTTP handler."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    window = 1 << 18
    with open(path, "rb") as f:
        while True:
            start = max(0, size - window)
            f.seek(start)
            chunk = f.read(size - start)
            lines = chunk.splitlines()
            if start > 0:
                lines = lines[1:]   # first line may be cut mid-record
            for raw in reversed(lines):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw.decode())
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue   # torn tail line of a crashed worker
                if isinstance(rec, dict) and \
                        isinstance(rec.get("metrics"), dict):
                    return rec["metrics"]
                if isinstance(rec, dict) and "counters" in rec:
                    return rec
            if start == 0:
                return None
            window *= 4


def load_meta(worker_dir: str) -> Dict:
    try:
        with open(os.path.join(worker_dir, META_FILE)) as f:
            return json.load(f)
    except Exception:
        return {}


class ClusterAggregator:
    """Host-0's federation point over the workers of one run.

    ``collect()`` pulls every worker's latest snapshot (HTTP when the
    worker is live, run-dir JSONL otherwise); ``cluster_snapshot()``
    merges them and folds in the straggler report;
    ``prometheus_text()`` renders the merged view for the
    ``/metrics/cluster`` route.
    """

    def __init__(self, sources: List[WorkerSource],
                 timeout_s: float = 2.0,
                 skew_threshold: float = 0.10):
        self.sources = list(sources)
        self.timeout_s = float(timeout_s)
        self.skew_threshold = float(skew_threshold)

    @classmethod
    def from_run_dir(cls, run_dir: str, offline: bool = False,
                     **kw) -> "ClusterAggregator":
        """Build sources from ``cluster.json`` (launcher manifest) or,
        absent that, by scanning ``host-*/`` worker dirs.

        ``offline=True`` (what ``obs_report --merge-hosts`` uses)
        reads ONLY the on-disk snapshots: a finished run's recorded
        host:port may have been reused by an unrelated process, and
        scraping it would silently merge someone else's registry into
        this run's report (it also avoids per-host connect timeouts on
        an scp'd run dir whose pod hostnames don't resolve)."""
        sources: List[WorkerSource] = []
        manifest = {}
        try:
            with open(os.path.join(run_dir, CLUSTER_FILE)) as f:
                manifest = json.load(f)
        except Exception:
            manifest = {}
        workers = manifest.get("workers")
        if workers:
            for w in workers:
                wdir = os.path.join(run_dir, w.get(
                    "dir", host_dir_name(w.get("process_index", 0))))
                meta = load_meta(wdir)
                port = meta.get("metrics_port", w.get("metrics_port"))
                hostname = meta.get("hostname",
                                    w.get("hostname", "localhost"))
                name = meta.get("name") or \
                    f"{hostname}/{w.get('process_index', 0)}"
                url = None if offline else (
                    f"http://{hostname}:{port}" if port else None)
                sources.append(WorkerSource(name, url=url, path=wdir))
        else:
            for entry in sorted(os.listdir(run_dir)):
                wdir = os.path.join(run_dir, entry)
                if not (entry.startswith("host-")
                        and os.path.isdir(wdir)):
                    continue
                meta = load_meta(wdir)
                name = meta.get("name") or entry
                port = meta.get("metrics_port")
                hostname = meta.get("hostname", "localhost")
                url = None if offline else (
                    f"http://{hostname}:{port}" if port else None)
                sources.append(WorkerSource(name, url=url, path=wdir))
        return cls(sources, **kw)

    def collect(self) -> Dict[str, Dict]:
        """host label -> latest snapshot; unreachable workers are
        skipped (a dead worker must not take the cluster view down).
        Workers are polled CONCURRENTLY, so a scrape of
        ``/metrics/cluster`` costs ~one timeout even with several
        unreachable hosts, not O(hosts) serial timeouts."""
        out: Dict[str, Dict] = {}
        if not self.sources:
            return out
        if len(self.sources) == 1:
            snap = self.sources[0].snapshot(self.timeout_s)
            if snap is not None:
                out[self.sources[0].name] = snap
            return out
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=min(16, len(self.sources)),
                thread_name_prefix="zoo-cluster-pull") as pool:
            futs = [(src.name,
                     pool.submit(src.snapshot, self.timeout_s))
                    for src in self.sources]
            for name, fut in futs:
                try:
                    snap = fut.result()
                except Exception:
                    snap = None
                if snap is not None:
                    out[name] = snap
        return out

    def cluster_view(self) -> Tuple[Dict[str, Dict], Dict]:
        """One collect → (host_snaps, merged-with-report): the single
        source of truth shared by the live ``/metrics/cluster`` routes
        and the offline ``obs_report --merge-hosts`` path, so both
        views carry the same skew gauges AND the same expected-vs-
        missing host accounting."""
        host_snaps = self.collect()
        merged = merge_snapshots(host_snaps)
        report = straggler_report(host_snaps,
                                  skew_threshold=self.skew_threshold)
        # an unreachable worker degrades to its last flushed file, or
        # drops out entirely — either way the view must SAY so, not
        # just shrink: expected-vs-reporting is the alerting signal
        missing = sorted(set(s.name for s in self.sources)
                         - set(host_snaps))
        report["expected_hosts"] = len(self.sources)
        if missing:
            report["missing_hosts"] = missing
            log.warning(
                "cluster view is missing %d of %d workers: %s "
                "(no live endpoint and no flushed snapshot)",
                len(missing), len(self.sources), missing)
        merged["gauges"].update(cluster_gauges(report))
        merged["gauges"]["cluster_hosts_expected"] = float(
            len(self.sources))
        merged["gauges"]["cluster_hosts_missing"] = float(len(missing))
        merged["cluster"] = report
        return host_snaps, merged

    def cluster_snapshot(self) -> Dict:
        return self.cluster_view()[1]

    def prometheus_text(self) -> str:
        snap = self.cluster_snapshot()
        snap.pop("cluster", None)
        return snapshot_prometheus_text(snap)


# ------------------------------------------------------------ trace merge
def merge_traces(run_dir: str, out_path: Optional[str] = None) -> Dict:
    """Merge per-host Chrome traces into one cluster timeline.

    Each worker's tracer exports timestamps relative to its own start;
    its ``meta.json`` carries ``clock_anchor`` (the launcher's startup
    wall time, broadcast through the env) and the trace carries
    ``wall_time_origin`` (that worker's wall clock at tracer start).
    Aligning is a pure shift: ``ts += (wall_time_origin - anchor)``,
    so "t=0" of the merged timeline is the launcher start on every
    host.  Events are re-homed to ``pid = process_index`` with Chrome
    ``process_name`` metadata, so Perfetto renders one labelled track
    group per host.
    """
    events: List[Dict] = []
    anchors: List[float] = []
    hosts = 0
    # the manifest names THIS run's workers; a reused run_dir may hold
    # stale host-*/ dirs from an earlier, larger launch whose traces
    # must not contaminate the merge — dir scanning is the fallback
    # only when no manifest exists
    entries = None
    try:
        with open(os.path.join(run_dir, CLUSTER_FILE)) as f:
            manifest = json.load(f)
        entries = sorted(
            w.get("dir", host_dir_name(w.get("process_index", 0)))
            for w in manifest.get("workers", []))
    except Exception:
        entries = None
    if not entries:
        entries = sorted(os.listdir(run_dir))
    for entry in entries:
        wdir = os.path.join(run_dir, entry)
        if not (entry.startswith("host-") and os.path.isdir(wdir)):
            continue
        trace_path = os.path.join(wdir, TRACE_FILE)
        try:
            with open(trace_path) as f:
                doc = json.load(f)
        except Exception:
            continue
        meta = load_meta(wdir)
        pidx = int(meta.get("process_index",
                            entry.split("-", 1)[-1] or hosts))
        name = meta.get("name", entry)
        origin = float((doc.get("otherData") or {}).get(
            "wall_time_origin", 0.0))
        anchor = float(meta.get("clock_anchor", origin))
        anchors.append(anchor)
        shift_us = (origin - anchor) * 1e6
        hosts += 1
        events.append({"name": "process_name", "ph": "M", "pid": pidx,
                       "args": {"name": name}})
        for e in doc.get("traceEvents", []):
            ev = dict(e)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            ev["pid"] = pidx
            events.append(ev)
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "analytics_zoo_tpu.observability.aggregator",
            "hosts_merged": hosts,
            "clock_anchor": min(anchors) if anchors else 0.0,
        },
    }
    if out_path:
        _atomic_dump_json(merged, out_path)
    return merged


def _atomic_dump_json(doc: Dict, out_path: str) -> None:
    """Local twin of ``common.fsutil.atomic_write_text`` (pid-unique
    tmp + ``os.replace``): this module is path-loaded by obs_report
    with NO package on sys.path, so it cannot fold onto fsutil — same
    carve-out as resilience/detector.py's stdlib-only contract."""
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)


# ---------------------------------------------------------- request merge
def _load_reqtrace_module():
    """Path-load ``reqtrace.py`` beside this file.  This module is
    itself path-loaded by ``scripts/obs_report.py`` (where the package
    may not be importable at all), so the merge logic cannot use a
    package import — and reqtrace's module level is deliberately
    stdlib-only to make this load safe anywhere."""
    import importlib.util
    import sys
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "reqtrace.py")
    spec = importlib.util.spec_from_file_location(
        "_zoo_reqtrace_offline", path)
    mod = importlib.util.module_from_spec(spec)
    # must be registered BEFORE exec: dataclass field-annotation
    # resolution looks the defining module up in sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def merge_requests(run_dir: str,
                   out_path: Optional[str] = None) -> Dict:
    """Merge per-host ``requests.json`` request-timeline logs into one
    document: timelines sharing a trace_id across replicas (or the
    client process) are joined and re-anchored on the earliest
    ``wall0`` (see ``reqtrace.merge_timeline_dicts``).  Host selection
    follows :func:`merge_traces`: the ``cluster.json`` manifest names
    THIS run's workers; directory scanning is the fallback only when
    no manifest exists.  Accepts a single ``requests.json`` FILE path
    too (the loadgen's ``--requests-out`` artifact)."""
    docs: List[Dict] = []
    hosts = 0
    if os.path.isfile(run_dir):
        try:
            with open(run_dir) as f:
                docs.append(json.load(f))
            hosts = 1
        except Exception:
            pass
    else:
        entries = None
        try:
            with open(os.path.join(run_dir, CLUSTER_FILE)) as f:
                manifest = json.load(f)
            entries = sorted(
                w.get("dir", host_dir_name(w.get("process_index", 0)))
                for w in manifest.get("workers", []))
        except Exception:
            entries = None
        if not entries:
            entries = sorted(os.listdir(run_dir)) \
                if os.path.isdir(run_dir) else []
        for entry in entries:
            wdir = os.path.join(run_dir, entry)
            if not (entry.startswith("host-") and os.path.isdir(wdir)):
                continue
            try:
                with open(os.path.join(wdir, REQUESTS_FILE)) as f:
                    docs.append(json.load(f))
                hosts += 1
            except Exception:
                continue
    reqtrace = _load_reqtrace_module()
    merged = {
        "kind": "zoo_request_timelines",
        "hosts_merged": hosts,
        "kept": sum(int(d.get("kept", 0)) for d in docs),
        "dropped": sum(int(d.get("dropped", 0)) for d in docs),
        "timelines": reqtrace.merge_timeline_dicts(docs),
    }
    if out_path:
        _atomic_dump_json(merged, out_path)
    return merged


# --------------------------------------------------- worker-side bring-up
# bring-up state is check-then-act shared between the caller's thread,
# atexit, and tests' reset — the lock makes init idempotence and
# init-vs-reset ordering actually atomic
_worker_state: Dict = {}
_worker_lock = threading.Lock()


def init_worker_observability(run_dir: Optional[str] = None,
                              process_index: Optional[int] = None,
                              metrics_port: Optional[int] = None,
                              start_server: bool = True,
                              register_atexit: bool = True
                              ) -> Optional[str]:
    """Worker half of the plane, driven by the launcher's env contract.

    Reads ``ZOO_TPU_RUN_DIR`` / ``ZOO_TPU_PROCESS_ID`` /
    ``ZOO_TPU_METRICS_PORT`` / ``ZOO_TPU_CLOCK_ANCHOR`` (explicit args
    override), then:

    1. stamps the immutable ``host``/``process_index`` const labels on
       the process registry,
    2. creates ``<run_dir>/host-<k>/`` and writes ``meta.json``,
    3. starts a ``MetricsServer`` on the injected port (host 0
       additionally gets the :class:`ClusterAggregator` attached, so
       its endpoint serves ``/metrics/cluster``),
    4. registers an atexit flush (final ``metrics.jsonl`` snapshot +
       ``trace.json``) so offline aggregation works even for workers
       that die between scrapes.

    Idempotent; returns the worker dir (None when no run dir is
    configured).  Imports the package lazily — this module must stay
    loadable without jax.
    """
    with _worker_lock:
        # idempotence check and the state commit below share the lock:
        # without it two racing bring-ups both pass the check and both
        # start a MetricsServer (the loser's listener leaks)
        if _worker_state.get("dir"):
            return _worker_state["dir"]
        run_dir = run_dir if run_dir is not None \
            else os.environ.get(ENV_RUN_DIR)
        if not run_dir:
            return None
        if process_index is None:
            process_index = int(os.environ.get(ENV_PROCESS_ID, "0"))
        if metrics_port is None:
            raw = os.environ.get(ENV_METRICS_PORT)
            metrics_port = int(raw) if raw else 0
        anchor = float(os.environ.get(ENV_CLOCK_ANCHOR, time.time()))
        hostname = socket.gethostname()
        name = f"{hostname}/{process_index}"

        wdir = os.environ.get(ENV_METRICS_DIR) or \
            os.path.join(run_dir, host_dir_name(process_index))
        os.makedirs(wdir, exist_ok=True)

        from analytics_zoo_tpu.observability.metrics import get_registry
        registry = get_registry()
        registry.set_const_labels(host=hostname,
                                  process_index=str(process_index))

        server = None
        if start_server:
            try:
                from analytics_zoo_tpu.observability.exporter import \
                    MetricsServer
                aggregator = None
                if process_index == 0:
                    aggregator = ClusterAggregator.from_run_dir(run_dir)
                    for src in aggregator.sources:
                        # host 0's own snapshot comes straight from the
                        # in-process registry — no HTTP round trip to
                        # self
                        if src.name == name:
                            src._fetch = registry.snapshot
                server = MetricsServer(port=metrics_port,
                                       aggregator=aggregator).start()
                metrics_port = server.port
            except Exception:
                log.exception("worker metrics server failed to start")
                server = None

        meta = {
            "name": name,
            "hostname": hostname,
            "process_index": int(process_index),
            "pid": os.getpid(),
            "metrics_port": metrics_port,
            "clock_anchor": anchor,
            "started_unix": time.time(),
        }
        with open(os.path.join(wdir, META_FILE), "w") as f:
            json.dump(meta, f, indent=2)

        # the historical layer (ISSUE 18): a tsdb writer + jittered
        # background sampler in this worker's slot, so burn-rate /
        # drift questions have data the moment the run dir exists
        try:
            from analytics_zoo_tpu.common.config import get_config
            from analytics_zoo_tpu.observability import tsdb as _tsdb
            cfg = get_config()
            if bool(cfg.get("observability.tsdb", True)):
                _tsdb.init_tsdb(
                    os.path.join(wdir, _tsdb.TSDB_DIRNAME),
                    interval_s=float(
                        cfg.get("observability.tsdb_interval_s", 10.0)),
                    retention_bytes=int(float(cfg.get(
                        "observability.tsdb_retention_mb", 64))
                        * 1024 * 1024),
                    retention_age_s=float(cfg.get(
                        "observability.tsdb_retention_age_s", 86400.0)),
                    registry=registry)
        except Exception:
            log.exception("worker tsdb bring-up failed")

        # the black-box flight recorder (ISSUE 19): lifecycle events
        # spool to host-<k>/events.jsonl from the first breath, and
        # the crash hooks (atexit / excepthook / fatal signal) are
        # armed so any death leaves a blackbox.json
        try:
            from analytics_zoo_tpu.observability import \
                flightrec as _flightrec
            _flightrec.init_flightrec(
                wdir, process_index=int(process_index),
                clock_anchor=anchor)
        except Exception:
            log.exception("worker flight-recorder bring-up failed")

        _worker_state.update({"dir": wdir, "meta": meta,
                              "server": server, "run_dir": run_dir})
    if register_atexit:
        import atexit
        atexit.register(flush_worker_observability)
    log.info("cluster observability worker %s -> %s (port %s)",
             name, wdir, metrics_port)
    return wdir


def flush_worker_observability() -> Optional[str]:
    """Append a registry snapshot line and (re)write the Chrome trace
    into this worker's run-dir slot.  Safe to call repeatedly (epoch
    boundaries, atexit); no-op before :func:`init_worker_observability`."""
    wdir = _worker_state.get("dir")
    if not wdir:
        return None
    try:
        from analytics_zoo_tpu.observability.metrics import get_registry
        get_registry().write_jsonl(os.path.join(wdir, METRICS_FILE))
    except Exception:
        log.exception("worker metrics flush failed")
    try:
        from analytics_zoo_tpu.observability.tracing import get_tracer
        get_tracer().export_chrome_trace(os.path.join(wdir, TRACE_FILE))
    except Exception:
        log.exception("worker trace flush failed")
    try:
        from analytics_zoo_tpu.observability.reqtrace import \
            get_request_log
        get_request_log().export(os.path.join(wdir, REQUESTS_FILE))
    except Exception:
        log.exception("worker request-log flush failed")
    try:
        from analytics_zoo_tpu.observability.tsdb import \
            flush_active_tsdb
        flush_active_tsdb()   # the run dir ends on a fresh sample
    except Exception:
        log.exception("worker tsdb flush failed")
    try:
        from analytics_zoo_tpu.observability import \
            flightrec as _flightrec
        _flightrec.flush_active_flightrec(
            "flush",
            registry_snapshot=_flightrec._default_registry_snapshot(),
            request_snapshot=_flightrec._default_request_snapshot())
    except Exception:
        log.exception("worker blackbox flush failed")
    return wdir


def reset_worker_observability() -> None:
    """Drop worker bring-up state (test helper); stops the server."""
    with _worker_lock:
        server = _worker_state.get("server")
        if server is not None:
            try:
                server.stop()
            except Exception:
                pass
        _worker_state.clear()
    try:
        from analytics_zoo_tpu.observability.tsdb import reset_tsdb
        reset_tsdb()
    except Exception:
        pass
    try:
        from analytics_zoo_tpu.observability.flightrec import \
            reset_flightrec
        reset_flightrec()
    except Exception:
        pass
