"""Collective accounting: estimated bytes moved and time spent per
collective (gradient psum, FSDP all-gather, pipeline ppermute).

XLA inserts the collectives from sharding annotations, so there is no
call site to time directly — but the TRAFFIC is fully determined by
the sharding contract: a ring all-reduce of P bytes over n devices
moves ``2(n-1)/n * P`` bytes per device link, an all-gather moves
``(n-1)/n * P``, a pipeline tick ppermutes one microbatch of
activations per stage.  This module turns those identities plus the
CompileMonitor's cost-analysis byte counts into registry counters:

* ``collective_bytes_total{op}``   — estimated per-device link bytes
* ``collective_seconds_total{op}`` — bytes / ``observability.ici_gbps``
  (0 disables the time estimate — set it to your interconnect's
  per-link bandwidth to get collective seconds in the reports)
* ``collective_ops_total{op}``     — how many steps/applies were
  accounted

The cluster aggregator sums these across hosts into the
straggler/collective section of ``obs_report.py --merge-hosts``.

Estimates are HOST-SIDE and cheap (computed once per program build,
counters bumped per dispatch); they never touch device data.  Like all
observability code they must degrade to "fewer counters", never to an
exception on a hot path.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

log = logging.getLogger("analytics_zoo_tpu.observability")

# canonical op labels
OP_PSUM_GRADS = "psum_grads"          # data(+fsdp)-axis gradient sync
OP_ALL_GATHER_PARAMS = "all_gather_params"   # FSDP param regather
OP_PPERMUTE = "ppermute"              # pipeline activation baton

# help text shared with the traced pipeline_apply path — whichever
# site registers the family first, the HELP line reads the same
BYTES_PER_STEP_HELP = \
    "estimated per-device link bytes per dispatch, by op"


def ring_all_reduce_bytes(payload_bytes: float, n: int) -> float:
    """Per-device link traffic of a ring all-reduce (reduce-scatter +
    all-gather): 2(n-1)/n of the payload."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * float(payload_bytes)


def all_gather_bytes(payload_bytes: float, n: int) -> float:
    """Per-device link traffic of an all-gather of a sharded payload:
    each device receives the (n-1)/n it doesn't hold."""
    if n <= 1:
        return 0.0
    return (n - 1) / n * float(payload_bytes)


def _dtype_bytes(dtype_str: str) -> int:
    return {"bfloat16": 2, "float16": 2, "float32": 4,
            "float64": 8}.get(str(dtype_str), 4)


def estimate_train_step_collectives(params, mesh,
                                    grad_sync_dtype: str = "float32"
                                    ) -> Dict[str, float]:
    """Per-step collective bytes implied by the trainer's sharding
    contract: gradients psum over the data×fsdp axes (in
    ``grad_sync_dtype``), and — when fsdp > 1 — the forward/backward
    all-gathers that rematerialize the fsdp-sharded params.  Returns
    ``{op: bytes_per_step}`` (empty when the mesh has no cross-device
    data axes).  Imports jax lazily; pure host arithmetic."""
    import jax
    import numpy as np
    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    leaves = jax.tree_util.tree_leaves(params)
    n_elems = sum(int(np.prod(np.shape(leaf))) for leaf in leaves)
    dp = int(mesh.shape[mesh_lib.DATA_AXIS])
    fsdp = int(mesh.shape[mesh_lib.FSDP_AXIS])
    out: Dict[str, float] = {}
    sync = dp * fsdp
    if sync > 1 and n_elems:
        grad_bytes = n_elems * _dtype_bytes(grad_sync_dtype)
        out[OP_PSUM_GRADS] = ring_all_reduce_bytes(grad_bytes, sync)
    if fsdp > 1 and n_elems:
        # forward + backward each regather the sharded params once
        param_bytes = sum(
            int(np.prod(np.shape(leaf)))
            * _dtype_bytes(str(getattr(leaf, "dtype", "float32")))
            for leaf in leaves)
        out[OP_ALL_GATHER_PARAMS] = \
            2.0 * all_gather_bytes(param_bytes, fsdp)
    return out


def estimate_pipeline_ppermute_bytes(microbatch_bytes: float,
                                     num_stages: int,
                                     num_microbatches: int) -> float:
    """Per-device link bytes of one ``pipeline_apply``: every tick of
    the ``M + P - 1`` schedule ppermutes one microbatch of activations
    per stage, plus the P-1 rotations of the final output broadcast."""
    if num_stages <= 1:
        return 0.0
    ticks = num_microbatches + num_stages - 1
    # +1 rotation for last->0, then P-1 broadcast hops of the full
    # output block (num_microbatches microbatches)
    broadcast = num_stages * num_microbatches * float(microbatch_bytes)
    return ticks * float(microbatch_bytes) + broadcast


class _Instruments:
    """Per-op counter children, bound once per live registry: this
    runs on the per-step dispatch hot path, so repeat calls must not
    re-resolve config or re-take the registry lock (rebinds after
    ``reset_registry`` — tests — by keying the cache on the registry
    object)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registry = None
        self._gbps: Dict[Optional[float], float] = {}
        self._children: Dict = {}     # (op, gbps) -> bound children

    def _resolve_gbps(self, ici_gbps: Optional[float]) -> float:
        if ici_gbps is not None:
            return float(ici_gbps)
        try:
            from analytics_zoo_tpu.common.config import get_config
            return float(get_config().get(
                "observability.ici_gbps", 0.0) or 0.0)
        except Exception:
            return 0.0

    def _bind(self, reg, op: str, gbps: float):
        c_bytes = reg.counter(
            "collective_bytes_total",
            "estimated per-device link bytes moved by sharding-implied "
            "collectives (ring/all-gather identities over the mesh)",
            labels=("op",)).labels(op)
        c_ops = reg.counter(
            "collective_ops_total",
            "dispatches accounted into collective_bytes_total",
            labels=("op",)).labels(op)
        c_secs = reg.counter(
            "collective_seconds_total",
            "estimated seconds inside collectives: bytes / "
            "observability.ici_gbps (0 disables)",
            labels=("op",)).labels(op) if gbps > 0 else None
        g = reg.gauge(
            "collective_bytes_per_step", BYTES_PER_STEP_HELP,
            labels=("op",)).labels(op)
        return c_bytes, c_ops, c_secs, g

    def record(self, bytes_by_op: Dict[str, float],
               ici_gbps: Optional[float] = None,
               steps: int = 1) -> None:
        """``bytes_by_op`` is PER-STEP traffic; ``steps`` scales the
        cumulative counters for a fused dispatch while the per-step
        gauge stays per-step — so chunked/epoch-scan and per-step
        engines stay comparable in bench/report diffs."""
        if not bytes_by_op or steps <= 0:
            return
        from analytics_zoo_tpu.observability.metrics import get_registry
        reg = get_registry()
        with self._lock:
            if self._registry is not reg:
                self._registry = reg
                self._gbps.clear()
                self._children.clear()
            gbps = self._gbps.get(ici_gbps)
            if gbps is None:
                gbps = self._gbps[ici_gbps] = \
                    self._resolve_gbps(ici_gbps)
            for op, nbytes in bytes_by_op.items():
                handles = self._children.get((op, gbps))
                if handles is None:
                    handles = self._children[(op, gbps)] = \
                        self._bind(reg, op, gbps)
                c_bytes, c_ops, c_secs, g = handles
                total = float(nbytes) * steps
                c_bytes.inc(total)
                c_ops.inc(steps)
                if c_secs is not None:
                    c_secs.inc(total / (gbps * 1e9))
                g.set(float(nbytes))


_instruments = _Instruments()


def record_step_collectives(bytes_by_op: Dict[str, float],
                            ici_gbps: Optional[float] = None,
                            steps: int = 1) -> None:
    """Bump the collective counters for a dispatch covering ``steps``
    training steps of per-step traffic ``bytes_by_op``.  Never
    raises."""
    try:
        _instruments.record(bytes_by_op, ici_gbps, steps)
    except Exception:
        log.debug("collective accounting failed", exc_info=True)
