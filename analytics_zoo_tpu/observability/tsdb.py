"""Embedded append-only telemetry time-series store (ISSUE 18).

The registry (PR 1) and the cluster federation (PR 4) answer "what is
the value NOW"; this module gives the platform a memory so the SLO
engine (``observability/slo.py``) and the drift watch
(``observability/drift.py``) can answer "what happened over the last
window".  One writer per process appends **segment files** under the
PR 4 run-dir host slot (``host-<k>/tsdb/seg-*.jsonl``), fed by a
background sampler that scrapes the in-process registry snapshot on a
jittered interval and once more at ``flush_worker_observability``.

Design points (sized for an embedded store, not a Prometheus):

* **Delta-encoded counters** — each sample records counter deltas
  against the previous sample; a segment's first sample (and any
  sample observing a counter reset) is a ``full`` sample carrying
  absolute values, so every segment is self-describing and a torn or
  deleted predecessor never corrupts reconstruction.
* **Ring retention** — segments roll at a byte/age bound and the
  oldest closed segments are deleted once the directory exceeds the
  byte or age budget: disk use is bounded no matter how long the
  service runs.
* **Crash safety** — the same torn-tail discipline as the training
  summaries' ``_ScalarWriter``: reopening seals a torn final line
  onto its own line, and readers skip unparseable lines instead of
  failing, so a SIGKILL mid-append costs at most one sample.
* **Histograms are flattened at scrape time** into counter series
  (``<name>_count``, ``<name>_sum``, ``<name>_bucket{le=...}``) and
  quantile gauges (``<name>_p50/p95/p99``) — the bucket counters are
  exactly what the burn-rate math needs for latency objectives.

CONTRACT: stdlib-only at module level, loadable by file path (the
``aggregator.py``/``reqtrace.py`` contract) so ``obs_report --slo``
renders run dirs without importing jax.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TSDB_SCHEMA",
    "TSDB_DIRNAME",
    "SeriesStore",
    "TsdbSampler",
    "TsdbWriter",
    "flatten_snapshot",
    "flush_active_tsdb",
    "get_active_tsdb",
    "init_tsdb",
    "parse_series_key",
    "read_samples",
    "reset_tsdb",
    "series_matches",
]

TSDB_SCHEMA = 1
TSDB_DIRNAME = "tsdb"
_SEGMENT_PREFIX = "seg-"


# ---------------------------------------------------------------- keys
def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``name{a="x",b="y"}`` -> (name, {a: x, b: y}).

    Local twin of ``aggregator.parse_series_key`` so this module stays
    standalone-loadable; the formats are identical by construction
    (both parse what ``metrics._format_labels`` emits)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        labels[k.strip()] = v.strip().strip('"')
    return name, labels


def format_series_key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def series_matches(selector: str, key: str) -> bool:
    """A selector matches a series when the names are equal and every
    selector label is present with the same value (extra series labels
    are fine — that is what lets one ``serving_errors_total`` selector
    cover per-endpoint children).  ``""``/``"*"`` match everything."""
    if selector in ("", "*", None):
        return True
    sname, slabels = parse_series_key(selector)
    kname, klabels = parse_series_key(key)
    if sname != kname:
        return False
    return all(klabels.get(k) == v for k, v in slabels.items())


# ------------------------------------------------------------- flatten
def flatten_snapshot(snap: Dict[str, Any]) -> Tuple[Dict[str, float],
                                                    Dict[str, float]]:
    """Registry ``snapshot()`` -> (counter series, gauge series).

    Histogram families become the Prometheus-shaped counter triplet
    (``_count``, ``_sum``, per-bucket cumulative ``_bucket{le=...}``
    with the implicit ``+Inf`` bucket) plus quantile gauges."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    for key, val in (snap.get("counters") or {}).items():
        counters[key] = float(val)
    for key, val in (snap.get("gauges") or {}).items():
        gauges[key] = float(val)
    for key, h in (snap.get("histograms") or {}).items():
        name, labels = parse_series_key(key)
        counters[format_series_key(name + "_count", labels)] = float(
            h.get("count", 0))
        counters[format_series_key(name + "_sum", labels)] = float(
            h.get("sum", 0.0))
        les = h.get("le") or []
        cum = h.get("cum") or []
        for le, c in zip(les, cum):
            blabels = dict(labels)
            blabels["le"] = f"{float(le):g}"
            counters[format_series_key(name + "_bucket",
                                       blabels)] = float(c)
        blabels = dict(labels)
        blabels["le"] = "+Inf"
        counters[format_series_key(name + "_bucket", blabels)] = float(
            h.get("count", 0))
        for q in ("p50", "p95", "p99"):
            if h.get(q) is not None:
                gauges[format_series_key(f"{name}_{q}",
                                         labels)] = float(h[q])
    return counters, gauges


# -------------------------------------------------------------- writer
class TsdbWriter:
    """Appends scrape samples to ring-retained segment files.

    One writer owns one directory (conventionally
    ``<run_dir>/host-<k>/tsdb``).  Thread-safe: the sampler thread and
    a flush call may append concurrently."""

    def __init__(self, directory: str, *,
                 retention_bytes: int = 64 * 1024 * 1024,
                 retention_age_s: float = 86400.0,
                 segment_max_bytes: int = 256 * 1024,
                 segment_max_age_s: float = 600.0,
                 recent_samples: int = 4096,
                 clock: Callable[[], float] = time.time):
        self.dir = directory
        self.retention_bytes = int(retention_bytes)
        self.retention_age_s = float(retention_age_s)
        self.segment_max_bytes = int(segment_max_bytes)
        self.segment_max_age_s = float(segment_max_age_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._f = None
        self._seg_path: Optional[str] = None
        self._seg_created = 0.0
        self._seg_seq = 0
        self._last_counters: Optional[Dict[str, float]] = None
        self._last_t: Optional[float] = None
        self.segments_deleted = 0
        # the live ring /tsdb.json serves from (absolute counters)
        self._recent: deque = deque(maxlen=int(recent_samples))
        os.makedirs(self.dir, exist_ok=True)

    # -- segment lifecycle -------------------------------------------
    def _open_segment(self, now: float) -> None:
        self._seg_seq += 1
        name = f"{_SEGMENT_PREFIX}{int(now * 1000):013d}-{self._seg_seq:04d}.jsonl"
        self._seg_path = os.path.join(self.dir, name)
        self._f = open(self._seg_path, "a")
        self._seal_torn_line()
        header = {"tsdb_schema": TSDB_SCHEMA, "created": now}
        self._f.write(json.dumps(header) + "\n")
        self._f.flush()
        self._seg_created = now
        # a fresh segment must be self-describing: next sample is full
        self._last_counters = None

    def _seal_torn_line(self) -> None:
        """Same discipline as ``_ScalarWriter``: a crash mid-write can
        leave a torn final line; start appends on a fresh line so the
        torn record corrupts only itself."""
        try:
            if self._f is not None and self._f.tell() > 0:
                with open(self._seg_path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        self._f.write("\n")
                        self._f.flush()
        except OSError:
            pass

    def _segments(self) -> List[str]:
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.startswith(_SEGMENT_PREFIX)
                     and n.endswith(".jsonl")]
        except OSError:
            return []
        return sorted(os.path.join(self.dir, n) for n in names)

    def _roll_if_needed(self, now: float) -> None:
        if self._f is None:
            self._open_segment(now)
            return
        size = 0
        try:
            size = self._f.tell()
        except (OSError, ValueError):
            pass
        if (size >= self.segment_max_bytes
                or now - self._seg_created >= self.segment_max_age_s):
            self._f.close()
            self._open_segment(now)

    def _enforce_retention(self, now: float) -> None:
        segs = self._segments()
        sizes = {}
        for p in segs:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
        total = sum(sizes.values())
        for p in list(segs):
            if p == self._seg_path:
                break           # never delete the active segment
            age = now - self._segment_created_time(p)
            if total > self.retention_bytes or age > self.retention_age_s:
                try:
                    os.remove(p)
                    self.segments_deleted += 1
                    total -= sizes[p]
                except OSError:
                    pass
            else:
                break           # segments are time-ordered: done

    @staticmethod
    def _segment_created_time(path: str) -> float:
        base = os.path.basename(path)[len(_SEGMENT_PREFIX):]
        try:
            return int(base.split("-", 1)[0]) / 1000.0
        except ValueError:
            return 0.0

    # -- appends ------------------------------------------------------
    def append(self, snapshot: Dict[str, Any],
               now: Optional[float] = None) -> None:
        """Record one registry snapshot as a sample."""
        now = self._clock() if now is None else float(now)
        counters, gauges = flatten_snapshot(snapshot)
        with self._lock:
            self._roll_if_needed(now)
            full = self._last_counters is None
            if not full:
                # a reset (registry restart) would need a negative
                # delta — switch to a full sample instead so absolute
                # reconstruction never goes negative
                for key, val in counters.items():
                    if val < self._last_counters.get(key, 0.0):
                        full = True
                        break
            if full:
                rec = {"t": now, "full": True, "c": counters,
                       "g": gauges}
            else:
                deltas = {}
                for key, val in counters.items():
                    prev = self._last_counters.get(key, 0.0)
                    if val != prev:
                        deltas[key] = round(val - prev, 9)
                rec = {"t": now, "c": deltas, "g": gauges}
            self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._f.flush()
            self._last_counters = dict(counters)
            self._last_t = now
            self._recent.append({"t": now, "counters": dict(counters),
                                 "gauges": dict(gauges)})
            self._enforce_retention(now)

    # -- reads --------------------------------------------------------
    def recent_samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._recent)

    def total_bytes(self) -> int:
        total = 0
        for p in self._segments():
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -------------------------------------------------------------- reader
def _iter_segment_samples(path: str) -> Iterable[Dict[str, Any]]:
    """Yield absolute-counter samples from one segment; a torn or
    corrupt line is skipped (costs one sample, never the segment)."""
    abs_counters: Dict[str, float] = {}
    have_base = False
    try:
        f = open(path)
    except OSError:
        return
    with f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "tsdb_schema" in rec:
                continue
            if "t" not in rec:
                continue
            if rec.get("full"):
                abs_counters = {k: float(v)
                                for k, v in (rec.get("c") or {}).items()}
                have_base = True
            elif have_base:
                for k, d in (rec.get("c") or {}).items():
                    abs_counters[k] = abs_counters.get(k, 0.0) + float(d)
            else:
                # segment lost its full base (torn header region):
                # deltas alone cannot reconstruct — skip until a full
                continue
            yield {"t": float(rec["t"]),
                   "counters": dict(abs_counters),
                   "gauges": {k: float(v)
                              for k, v in (rec.get("g") or {}).items()}}


def read_samples(directory: str) -> List[Dict[str, Any]]:
    """All samples of one tsdb directory (or a ``host-<k>`` slot, or a
    run dir containing ``host-*/tsdb``), time-ordered."""
    roots = []
    if os.path.isdir(os.path.join(directory, TSDB_DIRNAME)):
        roots.append(os.path.join(directory, TSDB_DIRNAME))
    elif os.path.isdir(directory):
        names = sorted(os.listdir(directory))
        host_roots = [os.path.join(directory, n, TSDB_DIRNAME)
                      for n in names if n.startswith("host-")]
        host_roots = [r for r in host_roots if os.path.isdir(r)]
        roots.extend(host_roots if host_roots else [directory])
    out: List[Dict[str, Any]] = []
    for root_i, root in enumerate(roots):
        stream = f"s{root_i}"
        try:
            segs = sorted(n for n in os.listdir(root)
                          if n.startswith(_SEGMENT_PREFIX))
        except OSError:
            continue
        for seg in segs:
            for sample in _iter_segment_samples(os.path.join(root, seg)):
                sample["stream"] = stream
                out.append(sample)
    out.sort(key=lambda s: s["t"])
    return out


class SeriesStore:
    """Query layer over a list of samples — the duck the SLO engine
    and the drift watch consume.

    Counters from different streams (hosts) are kept separate
    internally so one host's restart never looks like a cluster-wide
    reset; ``increase()`` sums reset-aware per-stream increases."""

    def __init__(self, samples: List[Dict[str, Any]]):
        self.samples = sorted(samples, key=lambda s: s["t"])
        # (stream, key) -> [(t, absolute value)]
        self._counter_series: Dict[Tuple[str, str],
                                   List[Tuple[float, float]]] = {}
        self._gauge_series: Dict[Tuple[str, str],
                                 List[Tuple[float, float]]] = {}
        for s in self.samples:
            stream = s.get("stream", "s0")
            t = s["t"]
            for key, v in (s.get("counters") or {}).items():
                self._counter_series.setdefault((stream, key),
                                                []).append((t, v))
            for key, v in (s.get("gauges") or {}).items():
                self._gauge_series.setdefault((stream, key),
                                              []).append((t, v))

    @classmethod
    def from_run_dir(cls, run_dir: str) -> "SeriesStore":
        return cls(read_samples(run_dir))

    @classmethod
    def from_writer(cls, writer: TsdbWriter) -> "SeriesStore":
        return cls(writer.recent_samples())

    def time_range(self) -> Tuple[Optional[float], Optional[float]]:
        if not self.samples:
            return None, None
        return self.samples[0]["t"], self.samples[-1]["t"]

    def counter_keys(self, selector: str) -> List[str]:
        return sorted({key for (_s, key) in self._counter_series
                       if series_matches(selector, key)})

    def gauge_keys(self, selector: str) -> List[str]:
        return sorted({key for (_s, key) in self._gauge_series
                       if series_matches(selector, key)})

    def increase(self, selector: str, t0: float, t1: float) -> float:
        """Total counter increase over ``(t0, t1]`` across every
        matching series, reset-aware: positive jumps count as deltas,
        and a counter reset (absolute value drops below the previous
        sample — a process restart) contributes its post-restart
        absolute value, Prometheus ``increase`` style, instead of a
        bogus negative — budget accounting survives sampler gaps and
        restarts."""
        total = 0.0
        for (_stream, key), pts in self._counter_series.items():
            if not series_matches(selector, key):
                continue
            prev = None
            for t, v in pts:
                if t > t1:
                    break
                if t <= t0:
                    prev = v
                    continue
                if prev is not None and v > prev:
                    total += v - prev
                elif prev is not None and v < prev:
                    # counter reset: the fresh stream grew 0 -> v
                    total += v
                elif prev is None:
                    # first point inside the window of a stream that
                    # has no pre-window baseline: the segment's full
                    # base covers growth before the window; count
                    # nothing until the next point
                    pass
                prev = v
        return total

    def gauge_points(self, selector: str,
                     t0: Optional[float] = None,
                     t1: Optional[float] = None
                     ) -> Dict[str, List[Tuple[float, float]]]:
        out: Dict[str, List[Tuple[float, float]]] = {}
        for (_stream, key), pts in self._gauge_series.items():
            if not series_matches(selector, key):
                continue
            sel = [(t, v) for t, v in pts
                   if (t0 is None or t >= t0)
                   and (t1 is None or t <= t1)]
            if sel:
                out.setdefault(key, []).extend(sel)
        for key in out:
            out[key].sort()
        return out

    def query(self, selector: str,
              t0: Optional[float] = None,
              t1: Optional[float] = None) -> Dict[str, List[Tuple[float, float]]]:
        """Raw points (counters absolute + gauges) for a selector —
        the ``/tsdb.json`` answer shape."""
        out = self.gauge_points(selector, t0, t1)
        for (_stream, key), pts in self._counter_series.items():
            if not series_matches(selector, key):
                continue
            sel = [(t, v) for t, v in pts
                   if (t0 is None or t >= t0)
                   and (t1 is None or t <= t1)]
            if sel:
                out.setdefault(key, []).extend(sel)
        for key in out:
            out[key].sort()
        return out


# ------------------------------------------------------------- sampler
class TsdbSampler:
    """Background scraper: registry ``snapshot()`` -> writer, on a
    jittered interval (±``jitter`` fraction, so a fleet of replicas
    never thunders in phase), plus on-demand ``sample_once`` calls
    from ``flush_worker_observability``.

    Scrape cost is measured per sample and kept in a bounded ring —
    ``overhead_p50()`` feeds the bench satellite's
    ``tsdb_sampler_p50_overhead_fraction`` self-gate."""

    def __init__(self, writer: TsdbWriter, *,
                 interval_s: float = 10.0,
                 jitter: float = 0.2,
                 registry: Any = None,
                 clock: Callable[[], float] = time.time,
                 perf: Callable[[], float] = time.perf_counter):
        self.writer = writer
        self.interval_s = float(interval_s)
        self.jitter = float(jitter)
        self._clock = clock
        self._perf = perf
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # appends land on the sampler thread while overhead_p50 sorts
        # on the caller's thread — deque append is atomic, but
        # iterating (sorted/list) during a concurrent append raises
        # RuntimeError, so both sides take this lock
        self._cost_lock = threading.Lock()
        self._scrape_costs: deque = deque(maxlen=512)
        self.samples_total = 0
        if registry is None:
            try:
                from analytics_zoo_tpu.observability.metrics import \
                    get_registry
                registry = get_registry()
            except ImportError:      # standalone (path-loaded) use
                registry = None
        self.registry = registry
        self._samples_counter = None
        self._scrape_gauge = None
        self._bytes_gauge = None
        if registry is not None:
            self._samples_counter = registry.counter(
                "tsdb_samples_total", "tsdb scrape samples appended")
            self._scrape_gauge = registry.gauge(
                "tsdb_last_scrape_seconds",
                "wall seconds the last tsdb scrape cost")
            self._bytes_gauge = registry.gauge(
                "tsdb_store_bytes", "bytes the tsdb segments occupy")

    def sample_once(self, now: Optional[float] = None) -> float:
        """One scrape+append; returns its cost in seconds."""
        if self.registry is None:
            return 0.0
        t0 = self._perf()
        snap = self.registry.snapshot()
        self.writer.append(snap, now=now)
        cost = self._perf() - t0
        with self._cost_lock:
            self._scrape_costs.append(cost)
        self.samples_total += 1
        if self._samples_counter is not None:
            self._samples_counter.inc()
            self._scrape_gauge.set(cost)
            self._bytes_gauge.set(self.writer.total_bytes())
        return cost

    def overhead_p50(self) -> float:
        with self._cost_lock:
            costs = sorted(self._scrape_costs)
        if not costs:
            return 0.0
        return costs[len(costs) // 2]

    def _loop(self) -> None:
        while not self._stop.is_set():
            lo, hi = 1.0 - self.jitter, 1.0 + self.jitter
            wait = self.interval_s * random.uniform(lo, hi)
            if self._stop.wait(max(0.01, wait)):
                break
            try:
                self.sample_once()
            except Exception:    # a scrape must never kill telemetry
                pass

    def start(self) -> "TsdbSampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="tsdb-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ----------------------------------------------------- process wiring
_active_lock = threading.Lock()
_active_writer: Optional[TsdbWriter] = None
_active_sampler: Optional[TsdbSampler] = None


def init_tsdb(directory: str, *, interval_s: float = 10.0,
              retention_bytes: int = 64 * 1024 * 1024,
              retention_age_s: float = 86400.0,
              registry: Any = None,
              start_sampler: bool = True) -> TsdbWriter:
    """Install the process-wide writer+sampler (idempotent per dir) —
    called by ``init_worker_observability`` for the worker's run-dir
    slot; the exporter's ``/tsdb.json`` serves the writer's ring."""
    global _active_writer, _active_sampler
    with _active_lock:
        if _active_writer is not None and _active_writer.dir == directory:
            return _active_writer
        if _active_sampler is not None:
            _active_sampler.stop()
        if _active_writer is not None:
            _active_writer.close()
        _active_writer = TsdbWriter(
            directory, retention_bytes=retention_bytes,
            retention_age_s=retention_age_s)
        _active_sampler = TsdbSampler(
            _active_writer, interval_s=interval_s, registry=registry)
        if start_sampler:
            _active_sampler.start()
        return _active_writer


def get_active_tsdb() -> Optional[TsdbWriter]:
    with _active_lock:
        return _active_writer


def get_active_sampler() -> Optional[TsdbSampler]:
    with _active_lock:
        return _active_sampler


def flush_active_tsdb() -> None:
    """One synchronous scrape — the ``flush_worker_observability``
    hook, so every flushed run dir ends on a fresh sample."""
    with _active_lock:
        sampler = _active_sampler
    if sampler is not None:
        try:
            sampler.sample_once()
        except Exception:
            pass


def reset_tsdb() -> None:
    global _active_writer, _active_sampler
    with _active_lock:
        if _active_sampler is not None:
            _active_sampler.stop()
            _active_sampler = None
        if _active_writer is not None:
            _active_writer.close()
            _active_writer = None
