"""Black-box flight recorder: structured lifecycle events (ISSUE 19).

The platform's control planes already *narrate* their decisions —
replica restarts, mesh reformations, lease steals, breaker trips,
scale decisions, quarantines — but as ad-hoc log lines and counters
scattered across processes.  This module gives those narrations one
structured spine: every subsystem reports lifecycle events through
:func:`record_event`, which

* keeps a bounded in-memory **ring** (the last-N context a blackbox
  dump embeds),
* spools each event **incrementally** to a crash-safe append-only
  ``host-<k>/events.jsonl`` journal with the same torn-tail
  discipline as ``tsdb.py`` — one flushed line per event, reopen
  seals a torn final line, readers skip unparseable lines — so even
  ``SIGKILL`` (chaos ``kill`` uses ``os._exit``; no atexit runs)
  leaves the journal readable up to the torn tail, and
* on orderly shutdown / fatal signal / unhandled exception dumps an
  enriched ``host-<k>/blackbox.json`` — last-N events, final registry
  snapshot, active request timelines, all-thread stacks (the
  ``faulthandler`` view, captured via ``sys._current_frames`` so it
  lands in JSON; genuinely fatal C-level signals are covered by
  ``faulthandler.enable`` into ``fatal.log``) — via atomic
  write-then-rename.

Journal format: the first line of each writer session is a header
(``{"events_schema": 1, ...}``) carrying pid/role/clock anchor; event
lines carry ``t`` (wall clock, clamped non-decreasing per session),
``seq`` (strictly increasing per session), ``kind`` (from the closed
:data:`EVENT_KINDS` vocabulary — ``metrics_lint --events`` enforces
it) and a ``d`` detail dict.  A respawn into the same slot appends a
new header; readers treat each header as a new session.

CONTRACT: stdlib-only at module level, loadable by file path (the
``aggregator.py``/``tsdb.py`` contract) so ``zoo-doctor`` and
``obs_report --incident`` read journals without importing jax.
"""

from __future__ import annotations

import atexit
import json
import os
import signal as _signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "EVENTS_SCHEMA",
    "EVENTS_FILENAME",
    "BLACKBOX_FILENAME",
    "EVENT_KINDS",
    "FlightRecorder",
    "flush_active_flightrec",
    "get_active_flightrec",
    "init_flightrec",
    "install_blackbox_hooks",
    "read_events",
    "read_journal",
    "record_event",
    "reset_flightrec",
]

EVENTS_SCHEMA = 1
EVENTS_FILENAME = "events.jsonl"
BLACKBOX_FILENAME = "blackbox.json"
FATAL_LOG_FILENAME = "fatal.log"

# local twins of the launcher's env contract (stdlib-only module: no
# package imports) — names must match parallel/launcher.py
ENV_METRICS_DIR = "ZOO_TPU_METRICS_DIR"
ENV_PROCESS_ID = "ZOO_TPU_PROCESS_ID"
ENV_CLOCK_ANCHOR = "ZOO_TPU_CLOCK_ANCHOR"

# The closed event vocabulary.  ``metrics_lint --events`` flags any
# journal line whose kind is not listed here — add the kind AND its
# docs/observability.md row when a new subsystem joins.
EVENT_KINDS = frozenset({
    # serving fleet lifecycle (serving/supervisor.py)
    "replica.spawn",        # a replica process (re)spawned
    "replica.exit",         # a replica exited, with classification
    "replica.retire",       # deliberate scale-down retirement
    "replica.kill",         # supervisor killed a replica (wedge/retire)
    "fleet.degraded",       # restart budget exhausted -> degraded.json
    "scale.up",             # autoscale decision, with its signals
    "scale.down",
    # serving data plane (serving/redis_client.py, serving/server.py)
    "breaker.transition",   # circuit breaker state change
    "quarantine",           # poison record -> dead-letter stream
    "dead_letter",          # non-shed dead letter (write_abandoned/poison)
    # elastic training (pipeline/estimator recovery loop)
    "train.failure",        # step failure, with detector classification
    "train.retry",          # policy decided RETRY
    "mesh.reform",          # mesh re-formed on the survivors
    "train.degraded",       # policy decided DEGRADE (checkpoint+queue)
    # batch tier (batchjobs/coordinator.py, batchjobs/manifest.py)
    "worker.respawn",       # coordinator respawned a dead worker
    "lease.claim",          # shard lease claimed (O_EXCL winner)
    "lease.steal",          # expired lease stolen, with recompute debt
    "lease.lost",           # renewal discovered the lease was stolen
    # watchdog + chaos (observability/watchdog.py, resilience/chaos.py)
    "watchdog.episode",     # nonfinite/divergence/plateau/stall/drift
    "chaos.trip",           # an armed fault fired at its site
    # recorder lifecycle
    "recorder.start",
    "blackbox.dump",
})


def _jsonable(value: Any) -> Any:
    """Best-effort coercion so a recorder call can never raise on an
    exotic detail value (events are forensics — drop fidelity, not
    the event)."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return str(value)


# ------------------------------------------------------------- recorder
class FlightRecorder:
    """Bounded event ring + append-only journal for one process.

    One recorder owns one directory (conventionally the worker's
    ``<run_dir>/host-<k>`` slot; control planes like the supervisor
    and batch coordinator point one at the run dir itself).  With no
    directory the ring still records — blackbox-on-demand and tests
    work without a run dir.  Thread-safe."""

    def __init__(self, directory: Optional[str] = None, *,
                 ring_size: int = 512,
                 role: str = "worker",
                 process_index: Optional[int] = None,
                 clock_anchor: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        self.dir = directory
        self.role = role
        self.process_index = process_index
        self.clock_anchor = clock_anchor
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring_size))
        self._seq = 0
        self._last_t = 0.0
        self._f = None
        self.path: Optional[str] = None
        self.events_total = 0
        self.dropped_writes = 0
        self._costs: deque = deque(maxlen=512)
        self._dumped_fatal = False
        if directory:
            self.path = os.path.join(directory, EVENTS_FILENAME)
            self._open_journal()

    # -- journal lifecycle -------------------------------------------
    def _open_journal(self) -> None:
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._f = open(self.path, "a")
            self._seal_torn_line()
            header: Dict[str, Any] = {
                "events_schema": EVENTS_SCHEMA,
                "created": self._clock(),
                "pid": os.getpid(),
                "role": self.role,
            }
            if self.process_index is not None:
                header["process_index"] = int(self.process_index)
            if self.clock_anchor is not None:
                header["clock_anchor"] = float(self.clock_anchor)
            self._f.write(json.dumps(header, sort_keys=True) + "\n")
            self._f.flush()
        except OSError:
            # a broken spool must never break the subsystem recording
            # into it — fall back to ring-only
            self._f = None
            self.dropped_writes += 1

    def _seal_torn_line(self) -> None:
        """Same discipline as ``TsdbWriter``: a crash mid-append can
        leave a torn final line; start this session on a fresh line so
        the torn record corrupts only itself."""
        try:
            if self._f is not None and self._f.tell() > 0:
                with open(self.path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        self._f.write("\n")
                        self._f.flush()
        except OSError:
            pass

    # -- appends ------------------------------------------------------
    def record(self, kind: str, /, **detail: Any) -> Dict[str, Any]:
        """Record one lifecycle event: ring + one flushed journal
        line.  Returns the event record (its ``seq`` is the id
        evidence citations use).  Never raises."""
        t0 = time.perf_counter()
        now = self._clock()
        with self._lock:
            self._seq += 1
            # non-decreasing within a session: the lint checks it, and
            # a small NTP step must not make the journal look torn
            if now < self._last_t:
                now = self._last_t
            self._last_t = now
            rec: Dict[str, Any] = {
                "t": round(now, 6), "seq": self._seq, "kind": str(kind)}
            if detail:
                rec["d"] = _jsonable(detail)
            self._ring.append(rec)
            self.events_total += 1
            if self._f is not None:
                try:
                    self._f.write(json.dumps(rec, sort_keys=True) + "\n")
                    self._f.flush()
                except (OSError, ValueError):
                    self.dropped_writes += 1
        self._costs.append(time.perf_counter() - t0)
        return rec

    def recent_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def overhead_p50(self) -> float:
        """Median wall cost of one ``record()`` — the bench
        ``flightrec_p50_overhead_fraction`` self-gate input."""
        if not self._costs:
            return 0.0
        costs = sorted(self._costs)
        return costs[len(costs) // 2]

    # -- blackbox -----------------------------------------------------
    def dump_blackbox(self, reason: str, *,
                      registry_snapshot: Optional[Dict[str, Any]] = None,
                      request_snapshot: Optional[Dict[str, Any]] = None,
                      error: Optional[str] = None,
                      fatal: bool = False) -> Optional[str]:
        """Write the enriched ``blackbox.json`` via atomic
        write-then-rename; returns its path (None without a spool
        dir).  A fatal dump (exception/signal) wins over a later
        orderly-shutdown dump — atexit skips once a fatal dump
        landed, so the crash picture is never papered over."""
        if self.dir is None:
            return None
        with self._lock:
            if fatal:
                self._dumped_fatal = True
            elif self._dumped_fatal:
                return None
            events = list(self._ring)
            doc: Dict[str, Any] = {
                "blackbox_schema": 1,
                "written": self._clock(),
                "reason": reason,
                "pid": os.getpid(),
                "role": self.role,
                "process_index": self.process_index,
                "clock_anchor": self.clock_anchor,
                "events_total": self.events_total,
                "dropped_writes": self.dropped_writes,
                "events": events,
            }
        if error:
            doc["error"] = error
        if registry_snapshot is not None:
            doc["registry"] = _jsonable(registry_snapshot)
        if request_snapshot is not None:
            doc["requests"] = _jsonable(request_snapshot)
        doc["stacks"] = _thread_stacks()
        path = os.path.join(self.dir, BLACKBOX_FILENAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, sort_keys=True, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def _thread_stacks() -> Dict[str, List[str]]:
    """Every live thread's Python stack, JSON-shaped — the same view
    ``faulthandler.dump_traceback`` prints, via
    ``sys._current_frames`` so it embeds in the blackbox document."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'thread')}:{ident}"
        out[label] = [line.rstrip("\n")
                      for line in traceback.format_stack(frame)]
    return out


# -------------------------------------------------------------- reader
def read_journal(path: str) -> Dict[str, Any]:
    """Parse one ``events.jsonl``: header sessions, events, and the
    torn-tail verdict.  A torn FINAL line is the crash-safety
    contract working (``torn_tail`` True, not an error); unparseable
    non-final lines are counted in ``skipped``."""
    headers: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    skipped = 0
    torn_tail = False
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return {"headers": headers, "events": events,
                "skipped": 0, "torn_tail": False}
    ends_complete = raw.endswith("\n")
    lines = [ln for ln in raw.split("\n") if ln.strip()]
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1 and not ends_complete:
                torn_tail = True
            else:
                skipped += 1
            continue
        if not isinstance(rec, dict):
            skipped += 1
            continue
        if "events_schema" in rec:
            headers.append(rec)
        elif "t" in rec and "kind" in rec:
            if headers:
                rec["session"] = len(headers) - 1
            events.append(rec)
        else:
            skipped += 1
    return {"headers": headers, "events": events,
            "skipped": skipped, "torn_tail": torn_tail}


def journal_paths(directory: str) -> List[tuple]:
    """``(stream, path)`` pairs for every journal under a run dir (the
    control plane's top-level ``events.jsonl`` plus each
    ``host-<k>/events.jsonl``), or a single host slot / file."""
    out: List[tuple] = []
    if os.path.isfile(directory):
        return [(os.path.basename(os.path.dirname(directory)) or "run",
                 directory)]
    top = os.path.join(directory, EVENTS_FILENAME)
    if os.path.isfile(top):
        out.append(("run", top))
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.startswith("host-"):
            continue
        p = os.path.join(directory, name, EVENTS_FILENAME)
        if os.path.isfile(p):
            out.append((name, p))
    return out


def read_events(directory: str) -> List[Dict[str, Any]]:
    """All events under a run dir (or host slot, or single journal),
    time-ordered, each tagged ``stream`` (source journal) and ``id``
    (``<stream>/e<seq>`` — the citation key ``zoo-doctor`` evidence
    uses)."""
    out: List[Dict[str, Any]] = []
    for stream, path in journal_paths(directory):
        parsed = read_journal(path)
        for ev in parsed["events"]:
            ev = dict(ev)
            ev["stream"] = stream
            ev["id"] = f"{stream}/e{ev.get('seq', '?')}"
            out.append(ev)
    out.sort(key=lambda e: (float(e.get("t", 0.0)), e.get("id", "")))
    return out


# ----------------------------------------------------- process wiring
_active_lock = threading.Lock()
_active_recorder: Optional[FlightRecorder] = None
_hooks_installed = False
_prev_excepthook = None


def init_flightrec(directory: Optional[str], *,
                   ring_size: int = 512,
                   role: str = "worker",
                   process_index: Optional[int] = None,
                   clock_anchor: Optional[float] = None,
                   install_hooks: bool = True) -> FlightRecorder:
    """Install the process-wide recorder (idempotent per dir) —
    called by ``init_worker_observability`` for the worker's run-dir
    slot.  Control planes owning their own run dir (supervisor, batch
    coordinator) construct private :class:`FlightRecorder` instances
    instead and leave the process-wide slot to the worker."""
    global _active_recorder
    with _active_lock:
        if (_active_recorder is not None
                and _active_recorder.dir == directory):
            return _active_recorder
        if _active_recorder is not None:
            _active_recorder.close()
        _active_recorder = FlightRecorder(
            directory, ring_size=ring_size, role=role,
            process_index=process_index, clock_anchor=clock_anchor)
    _active_recorder.record(
        "recorder.start", role=role,
        process_index=process_index if process_index is not None else -1)
    if install_hooks:
        install_blackbox_hooks()
    return _active_recorder


def get_active_flightrec(create: bool = True) -> Optional[FlightRecorder]:
    """The process-wide recorder; lazily created on first use so a
    subprocess that never ran ``init_worker_observability`` (batch
    worker, chaos victim) still journals into its
    ``ZOO_TPU_METRICS_DIR`` slot — or ring-only without one."""
    global _active_recorder
    with _active_lock:
        if _active_recorder is not None or not create:
            return _active_recorder
    directory = os.environ.get(ENV_METRICS_DIR) or None
    proc_id: Optional[int] = None
    anchor: Optional[float] = None
    try:
        if os.environ.get(ENV_PROCESS_ID):
            proc_id = int(os.environ[ENV_PROCESS_ID])
        if os.environ.get(ENV_CLOCK_ANCHOR):
            anchor = float(os.environ[ENV_CLOCK_ANCHOR])
    except ValueError:
        pass
    with _active_lock:
        if _active_recorder is None:
            _active_recorder = FlightRecorder(
                directory, process_index=proc_id, clock_anchor=anchor)
        return _active_recorder


def record_event(kind: str, /, **detail: Any) -> Dict[str, Any]:
    """THE one API every subsystem reports lifecycle events through.
    Cheap (one dict + one flushed line), thread-safe, never raises."""
    rec = get_active_flightrec()
    return rec.record(kind, **detail)


def flush_active_flightrec(reason: str = "flush",
                           registry_snapshot: Optional[Dict] = None,
                           request_snapshot: Optional[Dict] = None
                           ) -> Optional[str]:
    """Orderly-shutdown hook (``flush_worker_observability``): dump
    the blackbox for the spooling recorder, if any."""
    with _active_lock:
        rec = _active_recorder
    if rec is None or rec.dir is None:
        return None
    return rec.dump_blackbox(reason,
                             registry_snapshot=registry_snapshot,
                             request_snapshot=request_snapshot)


def _default_registry_snapshot() -> Optional[Dict[str, Any]]:
    try:
        from analytics_zoo_tpu.observability.metrics import get_registry
        return get_registry().snapshot()
    except Exception:   # noqa: BLE001 — standalone (path-loaded) use
        return None


def _default_request_snapshot() -> Optional[Dict[str, Any]]:
    try:
        from analytics_zoo_tpu.observability.reqtrace import \
            get_request_log
        return get_request_log().snapshot()
    except Exception:   # noqa: BLE001 — standalone (path-loaded) use
        return None


def _dump_active(reason: str, *, error: Optional[str] = None,
                 fatal: bool = False) -> None:
    with _active_lock:
        rec = _active_recorder
    if rec is None or rec.dir is None:
        return
    rec.dump_blackbox(reason,
                      registry_snapshot=_default_registry_snapshot(),
                      request_snapshot=_default_request_snapshot(),
                      error=error, fatal=fatal)


def _atexit_dump() -> None:
    _dump_active("shutdown")


def _excepthook(exc_type, exc, tb) -> None:
    try:
        err = "".join(traceback.format_exception_only(exc_type, exc))
        _dump_active(f"exception:{exc_type.__name__}",
                     error=err.strip(), fatal=True)
    except Exception:   # noqa: BLE001 — forensics must not mask the crash
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def install_blackbox_hooks() -> None:
    """Arm the blackbox: atexit (orderly shutdown), ``sys.excepthook``
    (unhandled exception, chained), SIGTERM (only when the process
    has no handler of its own — the serving worker's drain handler
    keeps precedence) and ``faulthandler`` into
    ``host-<k>/fatal.log`` for C-level fatal signals.  Idempotent."""
    global _hooks_installed, _prev_excepthook
    with _active_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
        rec = _active_recorder
        atexit.register(_atexit_dump)
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
    if rec is not None and rec.dir is not None:
        try:
            import faulthandler
            fh = open(os.path.join(rec.dir, FATAL_LOG_FILENAME), "a")
            faulthandler.enable(file=fh)
        except (OSError, ImportError):
            pass
    # fatal-signal dump: claim SIGTERM only if it is unhandled, and
    # re-deliver with the default disposition so exit semantics (the
    # detector's ``signal(TERM)`` classification) are preserved
    try:
        if (threading.current_thread() is threading.main_thread()
                and _signal.getsignal(_signal.SIGTERM)
                == _signal.SIG_DFL):
            def _on_term(signum, frame):   # noqa: ARG001
                _dump_active("signal:SIGTERM", fatal=True)
                _signal.signal(signum, _signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            _signal.signal(_signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass


def reset_flightrec() -> None:
    """Drop the process-wide recorder (tests)."""
    global _active_recorder
    with _active_lock:
        if _active_recorder is not None:
            _active_recorder.close()
            _active_recorder = None
