"""Process-wide metrics registry: Counter / Gauge / Histogram with
labels, Prometheus text exposition, and JSONL snapshots.

Reference posture: BigDL's driver printed a per-interval phase table
(the Metrics breakdown) and pushed Train/Validation scalars to
TensorBoard; operability lived in logs.  Here every subsystem shares
ONE registry so a single scrape (``/metrics``) or snapshot shows the
whole pipeline — training step latency, serving request latency, HBM
in use — in one place.

Dependency-free by design (no prometheus_client): the exposition
format is a few lines of text framing, and serving must not grow a
client-library dependency the container may not have.

Thread-safety: every mutation takes the owning metric's lock.  The
hot-path cost is one lock + float add, far below the dispatch cost of
any step it instruments.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Prometheus' default bucket ladder, widened down to 100us: TPU predict
# steps on a warm executable can sit well under 5ms.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    .0001, .00025, .0005, .001, .0025, .005, .01, .025, .05, .1, .25,
    .5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Ladder for epoch/long-job durations (sub-second to an hour) — shared
# by every train_epoch_seconds registration site.
EPOCH_BUCKETS: Tuple[float, ...] = (
    .1, .25, .5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    1800.0, 3600.0)


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_labels(names: Sequence[str], values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label_value(v)}"'
             for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    # integers print bare (Prometheus accepts either; bare reads better)
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One labeled time series of a metric family."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self.value += amount


class _GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        super().__init__()
        self.buckets = buckets
        self.counts = [0] * len(buckets)   # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            if i < len(self.counts):
                self.counts[i] += 1
            self.sum += v
            self.count += 1

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out

    def percentile(self, p: float) -> float:
        """Approximate percentile from bucket upper bounds (the bound
        of the first cumulative bucket covering p of the count)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = p / 100.0 * total
        acc = 0
        for bound, c in zip(self.buckets, counts):
            acc += c
            if acc >= target:
                return bound
        return self.buckets[-1] if self.buckets else 0.0


_KIND_CHILD = {"counter": _CounterChild, "gauge": _GaugeChild}


class _Family:
    """A named metric with a fixed label-name schema and one child per
    label-value combination."""

    def __init__(self, name: str, help: str, kind: str,
                 label_names: Tuple[str, ...],
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.kind = kind
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()
        if not label_names:
            # label-free series exist at zero from registration, so a
            # scrape before the first sample still shows them (rate()/
            # absent() alerting needs the series present) — matching
            # prometheus_client; labeled children appear on first use
            self.labels()

    def labels(self, *values, **kw):
        if kw:
            values = tuple(str(kw[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    values,
                    _HistogramChild(self.buckets)
                    if self.kind == "histogram"
                    else _KIND_CHILD[self.kind]())
        return child

    def _default(self):
        """The unlabeled child (only valid for label-free families)."""
        return self.labels()

    # convenience passthroughs so label-free metrics read naturally
    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self):
        return self._default().value

    def items(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Ordered collection of metric families with exposition/snapshot.

    ``counter``/``gauge``/``histogram`` are get-or-create: calling
    twice with the same name returns the same family (kind and label
    schema must match), so instrumentation sites never need to
    coordinate registration order.
    """

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, help: str, kind: str,
                       label_names: Iterable[str],
                       buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                       ) -> _Family:
        label_names = tuple(label_names)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help, kind, label_names, buckets)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.label_names != label_names:
            raise ValueError(
                f"metric {name!r} re-registered as {kind}"
                f"{label_names}, existing is {fam.kind}"
                f"{fam.label_names}")
        if kind == "histogram" and fam.buckets != tuple(sorted(buckets)):
            # a silently-discarded bucket ladder would misreport every
            # later observation — fail as loudly as a kind mismatch
            raise ValueError(
                f"histogram {name!r} re-registered with buckets "
                f"{tuple(sorted(buckets))}, existing has {fam.buckets}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> _Family:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> _Family:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> _Family:
        return self._get_or_create(name, help, "histogram", labels,
                                   buckets)

    # -------------------------------------------------------- exposition
    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        for fam in families:
            items = fam.items()
            if not items:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in sorted(items):
                if fam.kind == "histogram":
                    cum = child.cumulative()
                    for bound, c in zip(fam.buckets, cum):
                        lab = _format_labels(
                            fam.label_names, values,
                            ("le", _format_value(bound)))
                        lines.append(f"{fam.name}_bucket{lab} {c}")
                    lab = _format_labels(fam.label_names, values,
                                         ("le", "+Inf"))
                    lines.append(
                        f"{fam.name}_bucket{lab} {child.count}")
                    plain = _format_labels(fam.label_names, values)
                    lines.append(f"{fam.name}_sum{plain} "
                                 f"{_format_value(child.sum)}")
                    lines.append(f"{fam.name}_count{plain} "
                                 f"{child.count}")
                else:
                    lab = _format_labels(fam.label_names, values)
                    lines.append(f"{fam.name}{lab} "
                                 f"{_format_value(child.value)}")
        return "\n".join(lines) + "\n"

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> Dict:
        """JSON-friendly snapshot: counters/gauges as values, histograms
        as count/sum/percentile summaries (compact enough to embed in a
        bench artifact)."""
        out: Dict[str, Dict] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            for values, child in fam.items():
                key = fam.name
                if values:
                    key += _format_labels(fam.label_names, values)
                if fam.kind == "counter":
                    out["counters"][key] = child.value
                elif fam.kind == "gauge":
                    out["gauges"][key] = child.value
                else:
                    out["histograms"][key] = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "p50": child.percentile(50),
                        "p95": child.percentile(95),
                        "p99": child.percentile(99),
                    }
        return out

    def write_jsonl(self, path: str) -> None:
        """Append one timestamped snapshot line (crash-safe scrape log,
        same shape as utils/summary.py's JSONL scalars)."""
        rec = {"wall_time": time.time(), "metrics": self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")


_global_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem instruments into."""
    global _global_registry
    if _global_registry is None:
        with _registry_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry


def reset_registry() -> None:
    """Drop the process-wide registry (test helper)."""
    global _global_registry
    with _registry_lock:
        _global_registry = None
