"""Process-wide metrics registry: Counter / Gauge / Histogram with
labels, Prometheus text exposition, and JSONL snapshots.

Reference posture: BigDL's driver printed a per-interval phase table
(the Metrics breakdown) and pushed Train/Validation scalars to
TensorBoard; operability lived in logs.  Here every subsystem shares
ONE registry so a single scrape (``/metrics``) or snapshot shows the
whole pipeline — training step latency, serving request latency, HBM
in use — in one place.

Dependency-free by design (no prometheus_client): the exposition
format is a few lines of text framing, and serving must not grow a
client-library dependency the container may not have.

Thread-safety: every mutation takes the owning metric's lock.  The
hot-path cost is one lock + float add, far below the dispatch cost of
any step it instruments.
"""

from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")

# Per-metric label-cardinality ceiling: a per-request or per-host label
# exploding into unbounded series is the classic way an exporter OOMs.
# Children past the cap still accept writes (callers never break) but
# are not stored/exported; zoo_metrics_dropped_series_total{metric}
# counts them.  Overridable per registry or via
# observability.max_series_per_metric.
DEFAULT_MAX_SERIES = 1000

# Prometheus' default bucket ladder, widened down to 100us: TPU predict
# steps on a warm executable can sit well under 5ms.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    .0001, .00025, .0005, .001, .0025, .005, .01, .025, .05, .1, .25,
    .5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Ladder for epoch/long-job durations (sub-second to an hour) — shared
# by every train_epoch_seconds registration site.
EPOCH_BUCKETS: Tuple[float, ...] = (
    .1, .25, .5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    1800.0, 3600.0)


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_labels(names: Sequence[str], values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None,
                   const: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = [f'{n}="{_escape_label_value(v)}"'
             for n, v in list(const) + list(zip(names, values))]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    # integers print bare (Prometheus accepts either; bare reads better)
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _exemplar_suffix(ex: Optional[Tuple[str, float, float]]) -> str:
    """OpenMetrics exemplar clause appended to a bucket/counter line:
    ``# {trace_id="..."} <value> <unix ts>``."""
    if not ex:
        return ""
    tid, v, ts = ex
    return (f' # {{trace_id="{_escape_label_value(tid)}"}} '
            f"{_format_value(v)} {ts:.3f}")


class _Child:
    """One labeled time series of a metric family."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):
    __slots__ = ("value", "exemplar")

    def __init__(self):
        super().__init__()
        self.value = 0.0
        # last exemplar: (trace_id, observed increment, unix ts) — the
        # OpenMetrics bridge from a counter series to one inspectable
        # request timeline (reqtrace.py)
        self.exemplar: Optional[Tuple[str, float, float]] = None

    def inc(self, amount: float = 1.0,
            exemplar: Optional[str] = None) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self.value += amount
            if exemplar:
                self.exemplar = (str(exemplar), float(amount),
                                 time.time())


class _GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count", "exemplars")

    def __init__(self, buckets: Tuple[float, ...]):
        super().__init__()
        self.buckets = buckets
        self.counts = [0] * len(buckets)   # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        # per-bucket last exemplar (index len(buckets) = +Inf):
        # (trace_id, observed value, unix ts) — so a p99 bucket links
        # directly to one inspectable request timeline (reqtrace.py)
        self.exemplars: List[Optional[Tuple[str, float, float]]] = \
            [None] * (len(buckets) + 1)

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            if i < len(self.counts):
                self.counts[i] += 1
            self.sum += v
            self.count += 1
            if exemplar:
                self.exemplars[i] = (str(exemplar), v, time.time())

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out

    def percentile(self, p: float) -> float:
        """Approximate percentile from bucket upper bounds (the bound
        of the first cumulative bucket covering p of the count)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = p / 100.0 * total
        acc = 0
        for bound, c in zip(self.buckets, counts):
            acc += c
            if acc >= target:
                return bound
        return self.buckets[-1] if self.buckets else 0.0


_KIND_CHILD = {"counter": _CounterChild, "gauge": _GaugeChild}


class _Family:
    """A named metric with a fixed label-name schema and one child per
    label-value combination."""

    def __init__(self, name: str, help: str, kind: str,
                 label_names: Tuple[str, ...],
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 max_series: int = DEFAULT_MAX_SERIES,
                 on_drop=None):
        self.name = name
        self.help = help
        self.kind = kind
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self.max_series = int(max_series)
        self._on_drop = on_drop      # registry callback, called unlocked
        self._overflow_child: Optional[_Child] = None
        self._drop_warned = False
        # label combos already counted as dropped: the counter tracks
        # COMBINATIONS (what the help text promises), not writes, and
        # repeat writes to a dropped combo skip the lock/callback.
        # Bounded so a truly unbounded label can't grow this set either
        self._dropped_keys: set = set()
        self._max_dropped_keys = max(10 * self.max_series, 10_000)
        self._dropped_saturated = False
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()
        if not label_names:
            # label-free series exist at zero from registration, so a
            # scrape before the first sample still shows them (rate()/
            # absent() alerting needs the series present) — matching
            # prometheus_client; labeled children appear on first use
            self.labels()

    def labels(self, *values, **kw):
        if kw:
            values = tuple(str(kw[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        child = self._children.get(values)
        if child is None:
            # known-dropped combo: skip the lock and the drop
            # accounting entirely (hot-path writes to a capped series
            # must stay one set lookup, and the drop counter tracks
            # combinations, not writes).  Once the memo itself
            # saturates (a label so unbounded even 10x the cap of
            # combos flowed past), EVERY unknown combo short-circuits:
            # the counter undercounts beyond the memo bound rather
            # than reverting to per-write lock traffic — the loud
            # warning and >=bound counter value are signal enough
            if self._overflow_child is not None and (
                    self._dropped_saturated
                    or values in self._dropped_keys):
                return self._overflow_child
            dropped = False
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    if (self.max_series > 0
                            and len(self._children) >= self.max_series):
                        # cardinality cap: hand back a detached child —
                        # the caller's inc/observe still work, but the
                        # series is never stored or exported, so the
                        # exporter's memory stays bounded
                        if self._overflow_child is None:
                            self._overflow_child = self._new_child()
                        child = self._overflow_child
                        dropped = values not in self._dropped_keys
                        if dropped:
                            if len(self._dropped_keys) < \
                                    self._max_dropped_keys:
                                self._dropped_keys.add(values)
                            else:
                                # zoolint: disable=ATOM017 — deliberate saturating memo (see labels() docstring above): the unlocked fast-path guard may admit a few extra writers, each of which sets the same monotonic True under _lock
                                self._dropped_saturated = True
                    else:
                        child = self._children.setdefault(
                            values, self._new_child())
            if dropped:
                if not self._drop_warned:
                    self._drop_warned = True
                    log.warning(
                        "metric %r exceeded its %d-series label-"
                        "cardinality cap; further label combinations "
                        "are accepted but NOT exported (counted in "
                        "zoo_metrics_dropped_series_total) — an "
                        "unbounded label (request id? per-host key?) "
                        "is leaking into this metric",
                        self.name, self.max_series)
                if self._on_drop is not None:
                    try:
                        self._on_drop(self.name)
                    except Exception:  # accounting must never raise
                        pass
        return child

    def _new_child(self) -> _Child:
        return (_HistogramChild(self.buckets)
                if self.kind == "histogram"
                else _KIND_CHILD[self.kind]())

    def _default(self):
        """The unlabeled child (only valid for label-free families)."""
        return self.labels()

    # convenience passthroughs so label-free metrics read naturally
    # (the exemplar kw is forwarded only when given: gauges don't
    # take one, and a bare inc() must keep working on every kind)
    def inc(self, amount: float = 1.0,
            exemplar: Optional[str] = None) -> None:
        if exemplar is not None:
            self._default().inc(amount, exemplar=exemplar)
        else:
            self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        self._default().observe(value, exemplar=exemplar)

    @property
    def value(self):
        return self._default().value

    def items(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Ordered collection of metric families with exposition/snapshot.

    ``counter``/``gauge``/``histogram`` are get-or-create: calling
    twice with the same name returns the same family (kind and label
    schema must match), so instrumentation sites never need to
    coordinate registration order.
    """

    def __init__(self, max_series_per_metric: Optional[int] = None):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()
        # constant labels stamped on every exported series (host /
        # process_index identity in multi-host runs); immutable once set
        self._const_labels: Dict[str, str] = {}
        if max_series_per_metric is None:
            try:
                from analytics_zoo_tpu.common.config import get_config
                max_series_per_metric = int(get_config().get(
                    "observability.max_series_per_metric",
                    DEFAULT_MAX_SERIES))
            except Exception:
                max_series_per_metric = DEFAULT_MAX_SERIES
        self.max_series_per_metric = int(max_series_per_metric)

    # ---------------------------------------------------- const labels
    def set_const_labels(self, **labels) -> None:
        """Stamp identity labels (e.g. ``host``/``process_index``) onto
        every series this registry exports.  IMMUTABLE: re-setting a
        label to a different value raises — a worker's identity must
        not drift mid-run (the aggregator keys on it)."""
        clean = {str(k): str(v) for k, v in labels.items()}
        with self._lock:
            for k, v in clean.items():
                old = self._const_labels.get(k)
                if old is not None and old != v:
                    raise ValueError(
                        f"const label {k!r} already set to {old!r}; "
                        f"refusing to change it to {v!r} (worker "
                        "identity labels are immutable)")
            self._const_labels.update(clean)

    @property
    def const_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._const_labels)

    def _record_dropped_series(self, metric_name: str) -> None:
        # called from a family with NO lock held (see _Family.labels)
        self.counter(
            "zoo_metrics_dropped_series_total",
            "label-value combinations dropped by the per-metric "
            "cardinality cap (observability.max_series_per_metric)",
            labels=("metric",)).labels(metric_name).inc()

    def _get_or_create(self, name: str, help: str, kind: str,
                       label_names: Iterable[str],
                       buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                       ) -> _Family:
        label_names = tuple(label_names)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help, kind, label_names, buckets,
                              max_series=self.max_series_per_metric,
                              on_drop=self._record_dropped_series)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.label_names != label_names:
            raise ValueError(
                f"metric {name!r} re-registered as {kind}"
                f"{label_names}, existing is {fam.kind}"
                f"{fam.label_names}")
        if kind == "histogram" and fam.buckets != tuple(sorted(buckets)):
            # a silently-discarded bucket ladder would misreport every
            # later observation — fail as loudly as a kind mismatch
            raise ValueError(
                f"histogram {name!r} re-registered with buckets "
                f"{tuple(sorted(buckets))}, existing has {fam.buckets}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> _Family:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> _Family:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> _Family:
        return self._get_or_create(name, help, "histogram", labels,
                                   buckets)

    # -------------------------------------------------------- exposition
    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format 0.0.4.

        ``exemplars=True`` appends OpenMetrics-style exemplar clauses
        (``# {trace_id="..."} value ts``) to histogram bucket and
        counter lines that have one.  Off by default: the plain
        ``/metrics`` route keeps serving strict 0.0.4 (some scrapers
        reject the clause); the exporter serves the exemplar rendering
        under ``/metrics?exemplars=1``."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
            const = tuple(sorted(self._const_labels.items()))
        for fam in families:
            items = fam.items()
            if not items:
                continue
            # a family whose own schema names a const label (e.g. a
            # "host" label on a metric in a host-labelled registry)
            # wins: emitting both would be duplicate-label exposition,
            # which Prometheus rejects for the WHOLE scrape
            fconst = tuple((k, v) for k, v in const
                           if k not in fam.label_names)
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in sorted(items):
                if fam.kind == "histogram":
                    cum = child.cumulative()
                    for i, (bound, c) in enumerate(
                            zip(fam.buckets, cum)):
                        lab = _format_labels(
                            fam.label_names, values,
                            ("le", _format_value(bound)), const=fconst)
                        line = f"{fam.name}_bucket{lab} {c}"
                        if exemplars:
                            line += _exemplar_suffix(
                                child.exemplars[i])
                        lines.append(line)
                    lab = _format_labels(fam.label_names, values,
                                         ("le", "+Inf"), const=fconst)
                    line = f"{fam.name}_bucket{lab} {child.count}"
                    if exemplars:
                        line += _exemplar_suffix(
                            child.exemplars[len(fam.buckets)])
                    lines.append(line)
                    plain = _format_labels(fam.label_names, values,
                                           const=fconst)
                    lines.append(f"{fam.name}_sum{plain} "
                                 f"{_format_value(child.sum)}")
                    lines.append(f"{fam.name}_count{plain} "
                                 f"{child.count}")
                else:
                    lab = _format_labels(fam.label_names, values,
                                         const=fconst)
                    line = (f"{fam.name}{lab} "
                            f"{_format_value(child.value)}")
                    if exemplars and fam.kind == "counter":
                        line += _exemplar_suffix(
                            getattr(child, "exemplar", None))
                    lines.append(line)
        return "\n".join(lines) + "\n"

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> Dict:
        """JSON-friendly snapshot: counters/gauges as values, histograms
        as count/sum/percentile summaries (compact enough to embed in a
        bench artifact) plus their cumulative bucket counts (so the
        cluster aggregator can merge distributions exactly, not just
        count-weight the percentiles).  When const labels are set the
        snapshot carries them under a top-level ``"labels"`` key — keys
        inside the sections stay unprefixed, so single-process
        consumers are unaffected."""
        out: Dict[str, Dict] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        with self._lock:
            families = list(self._families.values())
            const = dict(self._const_labels)
        if const:
            out["labels"] = const
        for fam in families:
            for values, child in fam.items():
                key = fam.name
                if values:
                    key += _format_labels(fam.label_names, values)
                if fam.kind == "counter":
                    out["counters"][key] = child.value
                elif fam.kind == "gauge":
                    out["gauges"][key] = child.value
                else:
                    entry = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "p50": child.percentile(50),
                        "p95": child.percentile(95),
                        "p99": child.percentile(99),
                        # finite upper bounds + cumulative counts; the
                        # +Inf bucket is implicit ("count")
                        "le": list(fam.buckets),
                        "cum": child.cumulative(),
                    }
                    exs = {}
                    for i, ex in enumerate(child.exemplars):
                        if ex is None:
                            continue
                        bound = (_format_value(fam.buckets[i])
                                 if i < len(fam.buckets) else "+Inf")
                        exs[bound] = {"trace_id": ex[0],
                                      "value": ex[1]}
                    if exs:
                        entry["exemplars"] = exs
                    out["histograms"][key] = entry
        return out

    def write_jsonl(self, path: str) -> None:
        """Append one timestamped snapshot line (crash-safe scrape log,
        same shape as utils/summary.py's JSONL scalars)."""
        rec = {"wall_time": time.time(), "metrics": self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")


_global_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem instruments into."""
    global _global_registry
    if _global_registry is None:
        with _registry_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry


def reset_registry() -> None:
    """Drop the process-wide registry (test helper)."""
    global _global_registry
    with _registry_lock:
        _global_registry = None
