"""Request-scoped distributed tracing — per-request causal timelines
for the serving plane (Dapper-style tail forensics).

PRs 1/3/4 instrumented *aggregates* (histograms, spans, cluster
federation) and PR 13's verdict gates on p99-from-scheduled — but when
the verdict says "p99 blew the bound", nothing can say WHICH requests
were slow or WHERE inside the replica their time went.  This module is
that missing layer:

* :class:`TraceContext` — a ``trace_id`` + parent span id stamped by
  the client and propagated end-to-end: HTTP carries it in a
  ``traceparent``-style header (:data:`TRACE_HEADER`), Redis stream
  records carry it in a ``trace`` field (:data:`TRACE_FIELD`), and the
  PR 4 ``request_id`` correlation becomes one field of the context.
  Both transports carry the SAME wire string (:meth:`TraceContext
  .to_wire`), so propagation round-trips byte-identically — including
  send retries (the loadgen re-XADDs the same field dict) and PEL
  reclaim (XAUTOCLAIM hands the original fields back unchanged).
* :class:`RequestTimeline` — fixed lifecycle *stations* recorded on
  every request's path: ``enqueue``, ``transport_receive``,
  ``decode``, ``batch_queue_enter``, ``batch_compose`` (with batch id
  + fill ratio + co-rider count), ``dispatch``, ``device_done``,
  ``result_write`` / ``respond`` — plus per-iteration events
  (``prefill``, each ``decode_step``, ``retire`` cause) on the
  generative path.  Stations are offsets from the timeline's first
  mark, so the per-station segment breakdown sums to the measured
  latency by construction.
* :class:`RequestLog` — a bounded per-replica ring of finished
  timelines behind a tail-based sampler: errors, sheds and quarantines
  are ALWAYS kept, so are the slowest-K of each window; the healthy
  fast majority is down-sampled deterministically (every Nth).  The
  ring is served as ``/requests.json`` by the metrics server and
  flushed to the PR 4 run dir (``requests.json``) so
  ``obs_report --requests RUN_DIR`` merges replicas into a
  slowest-request waterfall.

Config knobs (all under ``observability.``, read at singleton
creation): ``reqtrace`` (default on), ``reqtrace_ring`` (ring
capacity), ``reqtrace_slowest_k`` / ``reqtrace_window_s`` /
``reqtrace_sample_every`` (tail-sampler shape).
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: the traceparent-style HTTP header both HTTP clients send and the
#: serving HTTP transport reads (W3C shape: version-traceid-spanid-flags)
TRACE_HEADER = "X-Zoo-Traceparent"
#: the Redis stream record field carrying the same wire string
TRACE_FIELD = "trace"

#: the fixed station vocabulary (docs/observability.md documents each);
#: generative requests additionally record prefill/decode_step/retire
STATIONS = ("enqueue", "transport_receive", "decode",
            "batch_queue_enter", "batch_compose", "dispatch",
            "device_done", "result_write", "respond",
            "prefill", "decode_step", "retire")

_WIRE_RE = re.compile(
    r"^(?P<ver>[0-9a-f]{2})-(?P<trace>[0-9a-f]{32})"
    r"-(?P<span>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$")
_HEX32_RE = re.compile(r"^[0-9a-f]{32}$")


@dataclass(frozen=True)
class TraceContext:
    """One request's identity on the wire: ``trace_id`` (32 lowercase
    hex) + the stamping side's span id (16 hex).  ``request_id`` is
    the PR 4 correlation key — carried alongside (records/bodies
    already have a ``request_id`` field), not inside the wire string,
    so the wire format stays a pure ``traceparent``."""

    trace_id: str
    span_id: str = "0" * 16
    request_id: Optional[str] = None

    @classmethod
    def new(cls, request_id: Optional[str] = None) -> "TraceContext":
        """Stamp a fresh context.  A uuid4-hex ``request_id`` (what
        the client/loadgen auto-generate) IS the trace id — one
        identifier correlates the loadgen record, the stream record,
        the timeline and the verdict's citation; anything else gets a
        fresh trace id with the request_id carried as a field."""
        if request_id and _HEX32_RE.match(request_id):
            tid = request_id
        else:
            tid = uuid.uuid4().hex
        return cls(trace_id=tid, span_id=uuid.uuid4().hex[:16],
                   request_id=request_id)

    def to_wire(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_wire(cls, value,
                  request_id: Optional[str] = None
                  ) -> Optional["TraceContext"]:
        """Parse a wire string (header value or Redis field; bytes
        accepted).  Malformed values return None — a request with a
        broken trace must still be served, just untraced."""
        if isinstance(value, bytes):
            try:
                value = value.decode()
            except UnicodeDecodeError:
                return None
        if not isinstance(value, str):
            return None
        m = _WIRE_RE.match(value.strip())
        if not m:
            return None
        return cls(trace_id=m.group("trace"), span_id=m.group("span"),
                   request_id=request_id)


@dataclass
class RequestTimeline:
    """One request's station timeline.  Station times are offsets (s)
    from the first mark; ``wall0`` anchors the timeline on the wall
    clock so the offline merge can align timelines recorded by
    different replicas of one run."""

    trace_id: str
    request_id: Optional[str] = None
    endpoint: str = ""
    transport: str = ""
    outcome: str = "pending"
    wall0: float = 0.0
    t0: float = 0.0
    stations: List[Dict[str, Any]] = field(default_factory=list)

    def mark(self, station: str, t: Optional[float] = None,
             **attrs) -> None:
        now = time.perf_counter() if t is None else t
        if not self.stations:
            self.t0 = now
            self.wall0 = time.time() - max(0.0, time.perf_counter()
                                           - now)
        entry: Dict[str, Any] = {"station": station,
                                 "t": max(0.0, now - self.t0)}
        if attrs:
            entry.update(attrs)
        self.stations.append(entry)

    @property
    def latency_s(self) -> float:
        if not self.stations:
            return 0.0
        return max(s["t"] for s in self.stations)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "endpoint": self.endpoint,
            "transport": self.transport,
            "outcome": self.outcome,
            "wall0": self.wall0,
            "latency_s": self.latency_s,
            "stations": list(self.stations),
        }


def _trace_id_of(trace) -> Optional[str]:
    """Accept a TraceContext, a timeline, or a bare trace_id string at
    every marking call site — instrumentation must never type-check
    its caller."""
    if trace is None:
        return None
    if isinstance(trace, str):
        return trace or None
    return getattr(trace, "trace_id", None)


class RequestLog:
    """Bounded per-replica timeline store with tail-based sampling.

    Active timelines live in a capped dict (a leak of never-finished
    requests must not grow without bound — the oldest active entry is
    evicted once the cap is hit); finished timelines pass the tail
    sampler into a ring.  All methods are thread-safe and cheap enough
    for the request hot path; when ``enabled`` is False every call is
    a no-op (the bench's ``reqtrace=off`` leg measures exactly this).
    """

    def __init__(self, capacity: int = 2048, slowest_k: int = 8,
                 window_s: float = 10.0, sample_every: int = 10,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.capacity = max(1, int(capacity))
        self.slowest_k = max(1, int(slowest_k))
        self.window_s = float(window_s)
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._active: Dict[str, RequestTimeline] = {}
        self._active_order: deque = deque()
        self._ring: deque = deque(maxlen=self.capacity)
        self._ok_seen = 0          # deterministic every-Nth sampling
        self._window_start = time.perf_counter()
        self._window_slowest: List[float] = []   # sorted ascending
        self.kept = 0
        self.dropped = 0

    # ------------------------------------------------------------ marking
    def begin(self, trace, *, transport: str = "",
              endpoint: str = "", station: Optional[str] = None,
              t: Optional[float] = None, **attrs
              ) -> Optional[RequestTimeline]:
        """Get-or-create the timeline for ``trace`` (idempotent per
        trace_id: the same request seen again — e.g. a PEL reclaim on
        the replica that originally read it — extends one timeline)."""
        tid = _trace_id_of(trace)
        if not self.enabled or not tid:
            return None
        with self._lock:
            tl = self._active.get(tid)
            if tl is None:
                tl = RequestTimeline(
                    trace_id=tid,
                    request_id=getattr(trace, "request_id", None))
                self._active[tid] = tl
                self._active_order.append(tid)
                # cap the active set: evict oldest-begun (they finish
                # as outcome=pending into the ring's sampler)
                while len(self._active) > self.capacity:
                    old = self._active_order.popleft()
                    lost = self._active.pop(old, None)
                    if lost is not None:
                        lost.outcome = "evicted"
                        self._keep_locked(lost)
            if transport:
                tl.transport = transport
            if endpoint:
                tl.endpoint = endpoint
        if station:
            tl.mark(station, t=t, **attrs)
        return tl

    def mark(self, trace, station: str, t: Optional[float] = None,
             **attrs) -> None:
        tid = _trace_id_of(trace)
        if not self.enabled or not tid:
            return
        with self._lock:
            tl = self._active.get(tid)
        if tl is not None:
            tl.mark(station, t=t, **attrs)

    def finish(self, trace, outcome: str,
               station: Optional[str] = None,
               t: Optional[float] = None, **attrs) -> None:
        """Close a timeline and run it through the tail sampler:
        non-ok outcomes (error / shed / quarantined / timeout) are
        always kept, so is anything among the slowest-K of the current
        window; the healthy remainder keeps every
        ``sample_every``-th."""
        tid = _trace_id_of(trace)
        if not self.enabled or not tid:
            return
        with self._lock:
            tl = self._active.pop(tid, None)
            if tl is None:
                return
            try:
                self._active_order.remove(tid)
            except ValueError:
                pass
        if station:
            tl.mark(station, t=t, **attrs)
        tl.outcome = outcome
        with self._lock:
            if self._sample_locked(tl):
                self._keep_locked(tl)
            else:
                self.dropped += 1

    # ------------------------------------------------------------ sampler
    def _sample_locked(self, tl: RequestTimeline) -> bool:
        if tl.outcome != "ok":
            return True
        now = time.perf_counter()
        if now - self._window_start > self.window_s:
            self._window_start = now
            self._window_slowest = []
        lat = tl.latency_s
        slow = self._window_slowest
        if len(slow) < self.slowest_k or lat >= slow[0]:
            # insert keeping ascending order, trim to K
            lo, hi = 0, len(slow)
            while lo < hi:
                mid = (lo + hi) // 2
                if slow[mid] < lat:
                    lo = mid + 1
                else:
                    hi = mid
            slow.insert(lo, lat)
            del slow[:-self.slowest_k]
            return True
        self._ok_seen += 1
        return self._ok_seen % self.sample_every == 0

    def _keep_locked(self, tl: RequestTimeline) -> None:
        self._ring.append(tl)
        self.kept += 1

    # ----------------------------------------------------------- querying
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view (the ``/requests.json`` payload): the kept
        ring plus keep/drop accounting, newest last."""
        with self._lock:
            ring = [tl.to_dict() for tl in self._ring]
            active = len(self._active)
        return {"kind": "zoo_request_timelines",
                "kept": self.kept, "dropped": self.dropped,
                "active": active, "capacity": self.capacity,
                "timelines": ring}

    def export(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f)
        return path

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._active_order.clear()
            self._ring.clear()
            self._ok_seen = 0
            self.kept = self.dropped = 0
            self._window_slowest = []
            self._window_start = time.perf_counter()


# ------------------------------------------------------------- singleton
_log: Optional[RequestLog] = None
_log_lock = threading.Lock()


def get_request_log() -> RequestLog:
    """Process-wide request log; shape read from config at creation
    (``observability.reqtrace*``).  ``observability.reqtrace`` off
    gives a disabled log whose every call is a cheap no-op."""
    global _log
    if _log is None:
        with _log_lock:
            if _log is None:
                from analytics_zoo_tpu.common.config import get_config
                cfg = get_config()
                _log = RequestLog(
                    capacity=int(cfg.get(
                        "observability.reqtrace_ring", 2048)),
                    slowest_k=int(cfg.get(
                        "observability.reqtrace_slowest_k", 8)),
                    window_s=float(cfg.get(
                        "observability.reqtrace_window_s", 10.0)),
                    sample_every=int(cfg.get(
                        "observability.reqtrace_sample_every", 10)),
                    enabled=bool(cfg.get(
                        "observability.reqtrace", True)))
    return _log


def reset_request_log() -> None:
    """Drop the singleton (tests; also how a config flip takes
    effect)."""
    global _log
    with _log_lock:
        _log = None


# -------------------------------------------------------- offline merge
def merge_timeline_dicts(docs: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """Merge timeline dicts from several replicas' ``requests.json``
    documents into one list, joining partial timelines that share a
    trace_id (e.g. the client process recorded ``enqueue`` while the
    replica recorded the serve stations).  Station offsets are
    re-anchored on the earliest ``wall0`` of the group, so cross-
    process segments (same host — the launcher's replicas) stay
    meaningful.  Pure dict-in/dict-out: the aggregator and obs_report
    call this without importing the package."""
    by_tid: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for doc in docs:
        for tl in (doc.get("timelines") or []):
            tid = tl.get("trace_id")
            if not tid:
                continue
            if tid not in by_tid:
                by_tid[tid] = []
                order.append(tid)
            by_tid[tid].append(tl)
    merged: List[Dict[str, Any]] = []
    for tid in order:
        parts = by_tid[tid]
        if len(parts) == 1:
            merged.append(dict(parts[0]))
            continue
        anchor = min(float(p.get("wall0", 0.0)) for p in parts)
        stations: List[Dict[str, Any]] = []
        for p in parts:
            shift = float(p.get("wall0", 0.0)) - anchor
            for s in (p.get("stations") or []):
                ns = dict(s)
                ns["t"] = float(s.get("t", 0.0)) + shift
                stations.append(ns)
        stations.sort(key=lambda s: s["t"])
        # the serve-side part owns the outcome; "pending" never wins
        outcome = "pending"
        for p in parts:
            if p.get("outcome") not in (None, "pending"):
                outcome = p["outcome"]
        out = dict(parts[0])
        out["outcome"] = outcome
        out["endpoint"] = next((p.get("endpoint") for p in parts
                                if p.get("endpoint")), "")
        out["transport"] = next((p.get("transport") for p in parts
                                 if p.get("transport")), "")
        out["wall0"] = anchor
        out["stations"] = stations
        out["latency_s"] = (max(s["t"] for s in stations)
                            if stations else 0.0)
        merged.append(out)
    return merged
