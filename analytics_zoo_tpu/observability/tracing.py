"""Span tracer: nested, thread-safe ``span("name")`` context managers
exported as Chrome-trace-format JSON (load in Perfetto / chrome://tracing).

The reference's time visibility was coarse driver-side ``Utils.timeIt``
log lines; ``jax.profiler`` covers the device side but not host
orchestration (batch assembly, checkpoint IO, Redis round trips).  Spans
fill that gap: a bounded in-memory ring of complete ("ph":"X") events,
cheap enough to leave on in production (two perf_counter reads and a
deque append per span).

Interval math uses ``time.perf_counter`` (monotonic); the wall-clock
epoch is recorded once so exported timestamps still line up with log
timestamps.

``span(..., jax_annotation=True)`` additionally brackets the block with
``jax.profiler.TraceAnnotation`` so the same name shows up inside a
captured device profile.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class Tracer:
    """Collects complete-span events into a bounded ring buffer.

    Nesting is tracked per-thread (a thread-local span stack) so
    concurrent serving/prefetch threads trace independently; Perfetto
    renders nesting from timestamp containment per tid, which the
    stack discipline guarantees.
    """

    def __init__(self, max_events: int = 200_000):
        self._events: deque = deque(maxlen=max_events)
        self._local = threading.local()
        self._lock = threading.Lock()
        # perf_counter origin pinned to a wall-clock instant so exported
        # ts values are "us since tracer start" and displayable
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self.enabled = True

    # ------------------------------------------------------------- spans
    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, jax_annotation: bool = False, **args):
        """Time a block as one trace event.  ``args`` become the
        event's Chrome-trace ``args`` dict (values must be
        JSON-serializable)."""
        if not self.enabled:
            yield self
            return
        ctx = contextlib.nullcontext()
        if jax_annotation:
            try:
                import jax.profiler
                ctx = jax.profiler.TraceAnnotation(name)
            except Exception:  # profiler unavailable — span still records
                pass
        stack = self._stack()
        stack.append(name)
        start = time.perf_counter()
        try:
            with ctx:
                yield self
        finally:
            dur = time.perf_counter() - start
            stack.pop()
            # the ring lock pairs with events()/clear(): appends must
            # not rely on the GIL for exclusion (free-threaded builds)
            with self._lock:
                self._events.append({
                    "name": name,
                    "ph": "X",
                    "ts": (start - self._t0) * 1e6,
                    "dur": dur * 1e6,
                    "pid": os.getpid(),
                    "tid": threading.get_ident(),
                    **({"args": args} if args else {}),
                })

    def complete(self, name: str, start_perf: float, duration_s: float,
                 **args) -> None:
        """Record a complete span from explicit timing (non-lexical
        scopes — e.g. an epoch whose end is reached via several code
        paths).  ``start_perf`` is a ``time.perf_counter()`` reading."""
        if not self.enabled:
            return
        with self._lock:
            self._events.append({
                "name": name, "ph": "X",
                "ts": (start_perf - self._t0) * 1e6,
                "dur": duration_s * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
                **({"args": args} if args else {}),
            })

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (``ph: "i"``)."""
        if not self.enabled:
            return
        with self._lock:
            self._events.append({
                "name": name, "ph": "i", "s": "t",
                "ts": (time.perf_counter() - self._t0) * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
                **({"args": args} if args else {}),
            })

    def flow_start(self, name: str, flow_id: str, **args) -> None:
        """Open a flow (``ph: "s"``) — a causal arrow OUT of the
        enclosing slice on this thread.  Pair with :meth:`flow_end`
        under the same ``flow_id`` on the receiving thread and
        Perfetto draws the arrow across the two lanes (e.g. a serving
        request handed from its transport thread to the batcher's
        executor thread).  ``cat`` is mandatory on flow events."""
        self._flow(name, flow_id, "s", args)

    def flow_end(self, name: str, flow_id: str, **args) -> None:
        """Close a flow (``ph: "f"`` with ``bp: "e"`` — bind to the
        ENCLOSING slice, the post-Chrome-M47 convention Perfetto
        expects)."""
        self._flow(name, flow_id, "f", args)

    def _flow(self, name: str, flow_id: str, ph: str, args) -> None:
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": ph, "cat": "flow",
            "id": str(flow_id),
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if ph == "f":
            ev["bp"] = "e"
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    def current_span(self) -> Optional[str]:
        stack = self._stack()
        return stack[-1] if stack else None

    def depth(self) -> int:
        return len(self._stack())

    # ------------------------------------------------------------ export
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> Dict:
        """The Chrome trace 'JSON Object Format': Perfetto and
        chrome://tracing both load it directly."""
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_time_origin": self._wall0,
                "producer": "analytics_zoo_tpu.observability",
            },
        }

    def export_chrome_trace(self, path: str) -> str:
        """Write the trace JSON; returns the path (``.json`` — open in
        https://ui.perfetto.dev or chrome://tracing)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # --------------------------------------------------- jax profiler tie
    @contextlib.contextmanager
    def jax_trace(self, log_dir: str, name: str = "jax_profile"):
        """Bracket a block with BOTH a span and a ``jax.profiler``
        trace capture: the span records where the capture sits in host
        time; the profile holds the device timeline (view either in
        Perfetto)."""
        import jax
        with self.span(name, log_dir=log_dir):
            jax.profiler.start_trace(log_dir)
            try:
                yield
            finally:
                jax.profiler.stop_trace()


_global_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _global_tracer
    if _global_tracer is None:
        with _tracer_lock:
            if _global_tracer is None:
                max_events = 200_000
                try:
                    from analytics_zoo_tpu.common.config import get_config
                    max_events = int(get_config().get(
                        "observability.trace_events", 200_000))
                except Exception:
                    pass
                _global_tracer = Tracer(max_events=max_events)
    return _global_tracer


def reset_tracer() -> None:
    """Drop the process-wide tracer (test helper)."""
    global _global_tracer
    with _tracer_lock:
        _global_tracer = None


def span(name: str, **kwargs):
    """Module-level convenience: ``with span("train_step"): ...`` on
    the process-wide tracer."""
    return get_tracer().span(name, **kwargs)
