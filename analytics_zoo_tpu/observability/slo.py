"""Declarative SLOs evaluated as multi-window multi-burn-rate error
budgets (ISSUE 18).

An :class:`SloObjective` names what "good" means over registry series
stored in the tsdb (``observability/tsdb.py``): an availability or
error-rate ratio over counter pairs, a latency quantile over a
histogram's bucket counters, or the freshness of a series.  The
evaluator is the Google-SRE burn-rate construction: the error budget
is ``1 - target`` per budget period (``window_s``); the **burn rate**
over a lookback window is ``bad_fraction / (1 - target)`` (1.0 =
spending exactly the sustainable budget); an alert level fires when
the burn rate exceeds its threshold in BOTH a long and a short window
(the long window proves the spend is real, the short window makes the
alert reset fast once the incident ends).  Defaults are the SRE
workbook's: page at 14.4× (1h + 5m), warn at 6× (6h + 30m) —
storm-compressed tests override the window lengths, never the math.

Objectives are label-keyed: a ``group_by`` label (``endpoint`` today,
a tenant dimension tomorrow) fans one objective out into one budget
per label value.

Specs load from YAML (``slo.yaml`` / a ``slos:`` section in
``config.yaml``) via the same hand-rolled subset parser discipline as
``ServingConfig.from_yaml`` — CONTRACT: stdlib-only, loadable by file
path, so ``obs_report --slo`` stays jax-free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

__all__ = [
    "ALERT_LEVELS",
    "BurnWindow",
    "SloAlertState",
    "SloEngine",
    "SloObjective",
    "SloStatus",
    "default_windows",
    "evaluate_timeline",
    "load_slo_yaml",
    "parse_slo_specs",
]

ALERT_LEVELS = {"ok": 0, "warn": 1, "page": 2}
_LEVEL_NAMES = {v: k for k, v in ALERT_LEVELS.items()}


@dataclass
class BurnWindow:
    """One (long, short) burn-rate window pair and the alert level it
    raises when BOTH exceed ``burn``."""
    name: str                    # alert level: "page" or "warn"
    burn: float                  # burn-rate threshold (>= fires)
    long_s: float
    short_s: float


def default_windows() -> List[BurnWindow]:
    """The SRE-workbook ladder (budget period 30d in the book; the
    thresholds are period-relative so they transfer unchanged)."""
    return [
        BurnWindow("page", 14.4, 3600.0, 300.0),
        BurnWindow("warn", 6.0, 21600.0, 1800.0),
    ]


@dataclass
class SloObjective:
    """What "good" means for one service dimension.

    objective kinds
        ``error_rate``       bad/total counter ratio (``bad``,``total``)
        ``availability``     1 - good/total (``good`` or ``bad``)
        ``latency_quantile`` fraction of requests over ``threshold_ms``
                             from ``<histogram>_bucket{le=...}`` counters
        ``freshness``        fraction of the window with no sample of
                             ``series`` within ``max_age_s``
    """
    name: str
    objective: str = "availability"
    target: float = 0.99
    window_s: float = 3600.0          # the budget period
    total: Optional[str] = None       # counter selectors
    bad: Optional[str] = None
    good: Optional[str] = None
    histogram: Optional[str] = None   # latency_quantile
    threshold_ms: float = 1000.0
    series: Optional[str] = None      # freshness
    max_age_s: float = 60.0
    group_by: Optional[str] = None    # label key to fan out on
    windows: List[BurnWindow] = field(default_factory=default_windows)
    recovery_hold_s: float = 0.0      # extra clear-side hysteresis

    def scaled(self, factor: float) -> "SloObjective":
        """A copy with every time window multiplied by ``factor`` —
        how compressed storm runs reuse production specs."""
        return SloObjective(
            name=self.name, objective=self.objective,
            target=self.target, window_s=self.window_s * factor,
            total=self.total, bad=self.bad, good=self.good,
            histogram=self.histogram, threshold_ms=self.threshold_ms,
            series=self.series, max_age_s=self.max_age_s * factor,
            group_by=self.group_by,
            windows=[BurnWindow(w.name, w.burn, w.long_s * factor,
                                w.short_s * factor)
                     for w in self.windows],
            recovery_hold_s=self.recovery_hold_s * factor)


@dataclass
class SloStatus:
    """One objective's (or one group's) evaluated state at ``t``."""
    name: str
    group: Optional[str]
    t: float
    alert: str
    burn: Dict[str, Dict[str, float]]   # window name -> {long, short}
    budget_remaining: float
    bad_fraction: float                 # over the budget period
    target: float
    detail: str = ""

    @property
    def slo_key(self) -> str:
        return self.name if not self.group else f"{self.name}/{self.group}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "group": self.group,
            "t": round(self.t, 6), "alert": self.alert,
            "burn": {w: {k: round(v, 6) for k, v in b.items()}
                     for w, b in self.burn.items()},
            "budget_remaining": round(self.budget_remaining, 6),
            "bad_fraction": round(self.bad_fraction, 9),
            "target": self.target, "detail": self.detail,
        }


# ------------------------------------------------------- alert state
class SloAlertState:
    """ok/warn/page with asymmetric hysteresis: a level fires the
    instant both of its windows exceed the threshold; it clears only
    after the burn stays below for ``recovery_hold_s`` (0 = the short
    window itself is the hysteresis, per the SRE construction)."""

    def __init__(self, recovery_hold_s: float = 0.0):
        self.recovery_hold_s = float(recovery_hold_s)
        self.level = 0
        self._clear_since: Optional[float] = None
        self.transitions: List[Tuple[float, str]] = []

    def update(self, now: float,
               firing_level: int) -> str:
        if firing_level >= self.level:
            if firing_level > self.level:
                self.level = firing_level
                self.transitions.append((now, _LEVEL_NAMES[self.level]))
            self._clear_since = None
        else:
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since >= self.recovery_hold_s:
                self.level = firing_level
                self._clear_since = None
                self.transitions.append((now, _LEVEL_NAMES[self.level]))
        return _LEVEL_NAMES[self.level]


# ------------------------------------------------------- evaluation
def _with_label(selector: Optional[str], key: str,
                value: str) -> Optional[str]:
    if selector is None:
        return None
    if "{" in selector:
        name, _, rest = selector.partition("{")
        inner = rest.rstrip("}")
        sep = "," if inner else ""
        return f'{name}{{{inner}{sep}{key}="{value}"}}'
    return f'{selector}{{{key}="{value}"}}'


class _Evaluator:
    """bad_fraction over an arbitrary window, per objective kind."""

    def __init__(self, store: Any, obj: SloObjective,
                 group: Optional[str] = None):
        self.store = store
        self.obj = obj
        self.group = group

    def _sel(self, selector: Optional[str]) -> Optional[str]:
        if self.group is not None and self.obj.group_by:
            return _with_label(selector, self.obj.group_by, self.group)
        return selector

    def bad_fraction(self, t0: float, t1: float) -> float:
        obj = self.obj
        if t1 <= t0:
            return 0.0
        if obj.objective in ("error_rate", "availability"):
            total = self.store.increase(self._sel(obj.total), t0, t1)
            if total <= 0:
                return 0.0       # no traffic spends no budget
            if obj.bad is not None:
                bad = self.store.increase(self._sel(obj.bad), t0, t1)
            else:
                good = self.store.increase(self._sel(obj.good), t0, t1)
                bad = max(0.0, total - good)
            return min(1.0, max(0.0, bad / total))
        if obj.objective == "latency_quantile":
            # suffix the bare histogram name FIRST, then apply the
            # group label: the stored keys are hist_count{endpoint=..}
            total = self.store.increase(
                self._sel(f"{obj.histogram}_count"), t0, t1)
            if total <= 0:
                return 0.0
            le = self._bucket_le(self._sel(f"{obj.histogram}_bucket"))
            if le is None:       # threshold beyond the ladder
                return 0.0
            good = self.store.increase(
                _with_label(self._sel(f"{obj.histogram}_bucket"),
                            "le", le), t0, t1)
            return min(1.0, max(0.0, (total - good) / total))
        if obj.objective == "freshness":
            return self._staleness_fraction(t0, t1)
        raise ValueError(f"unknown objective kind: {obj.objective!r}")

    def _bucket_le(self, bucket_sel: str) -> Optional[str]:
        """The smallest bucket bound >= threshold — requests at or
        under it are the 'good' events."""
        threshold_s = self.obj.threshold_ms / 1000.0
        best: Optional[float] = None
        for key in self.store.counter_keys(bucket_sel):
            _, labels = _parse_key(key)
            raw = labels.get("le", "")
            if raw in ("+Inf", "inf", ""):
                continue
            try:
                le = float(raw)
            except ValueError:
                continue
            if le >= threshold_s - 1e-12 and (best is None or le < best):
                best = le
        return None if best is None else f"{best:g}"

    def _staleness_fraction(self, t0: float, t1: float) -> float:
        obj = self.obj
        window = t1 - t0
        got = self.store.query(self._sel(obj.series),
                               t0 - obj.max_age_s, t1)
        pts = sorted(t for series_pts in got.values()
                     for t, _v in series_pts)
        if not pts:
            return 1.0           # never observed: fully stale
        covered = 0.0
        cursor = t0
        for t in pts:
            lo, hi = max(t, cursor), min(t + obj.max_age_s, t1)
            if hi > lo:
                covered += hi - lo
                cursor = hi      # intervals merge left-to-right
        return min(1.0, max(0.0, 1.0 - covered / window))


def _parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if part:
            k, _, v = part.partition("=")
            labels[k.strip()] = v.strip().strip('"')
    return name, labels


class SloEngine:
    """Evaluates objectives against a series store and keeps the
    per-objective (per-group) alert state between calls — the live
    consumer loop: supervisor/watchdog call ``evaluate`` on their
    cadence and read the statuses."""

    def __init__(self, objectives: Sequence[SloObjective], *,
                 registry: Any = None,
                 clock: Callable[[], float] = time.time):
        self.objectives = list(objectives)
        self._clock = clock
        self._states: Dict[Tuple[str, Optional[str]], SloAlertState] = {}
        self.registry = registry
        self._burn_gauge = None
        self._budget_gauge = None
        self._alert_gauge = None
        if registry is not None:
            self._bind_registry(registry)

    def _bind_registry(self, registry: Any) -> None:
        self.registry = registry
        self._burn_gauge = registry.gauge(
            "slo_burn_rate", "error-budget burn rate per window",
            labels=("slo", "window"))
        self._budget_gauge = registry.gauge(
            "slo_budget_remaining",
            "fraction of the period's error budget left",
            labels=("slo",))
        self._alert_gauge = registry.gauge(
            "slo_alert_state", "0 ok / 1 warn / 2 page",
            labels=("slo",))

    def _groups(self, store: Any, obj: SloObjective) -> List[Optional[str]]:
        if not obj.group_by:
            return [None]
        base = obj.total or obj.series or (
            f"{obj.histogram}_count" if obj.histogram else None)
        if base is None:
            return [None]
        keys = list(store.counter_keys(base))
        # freshness objectives usually watch gauges (heartbeat/up
        # series) — without this union group_by silently collapses
        # to one ungrouped budget
        gauge_keys = getattr(store, "gauge_keys", None)
        if gauge_keys is not None:
            keys.extend(gauge_keys(base))
        groups = set()
        for key in keys:
            _, labels = _parse_key(key)
            if obj.group_by in labels:
                groups.add(labels[obj.group_by])
        return sorted(groups) or [None]

    def evaluate(self, store: Any,
                 now: Optional[float] = None) -> List[SloStatus]:
        now = self._clock() if now is None else float(now)
        statuses: List[SloStatus] = []
        for obj in self.objectives:
            for group in self._groups(store, obj):
                statuses.append(self._evaluate_one(store, obj, group,
                                                   now))
        if self.registry is not None:
            self.publish(statuses)
        return statuses

    def _evaluate_one(self, store: Any, obj: SloObjective,
                      group: Optional[str], now: float) -> SloStatus:
        ev = _Evaluator(store, obj, group)
        budget = max(1e-12, 1.0 - obj.target)
        burn: Dict[str, Dict[str, float]] = {}
        firing = 0
        for w in obj.windows:
            long_frac = ev.bad_fraction(now - w.long_s, now)
            short_frac = ev.bad_fraction(now - w.short_s, now)
            b = {"long": long_frac / budget, "short": short_frac / budget}
            burn[w.name] = b
            if (b["long"] >= w.burn and b["short"] >= w.burn):
                firing = max(firing, ALERT_LEVELS.get(w.name, 1))
        state = self._states.setdefault(
            (obj.name, group), SloAlertState(obj.recovery_hold_s))
        alert = state.update(now, firing)
        period_frac = ev.bad_fraction(now - obj.window_s, now)
        status = SloStatus(
            name=obj.name, group=group, t=now, alert=alert,
            burn=burn,
            budget_remaining=1.0 - period_frac / budget,
            bad_fraction=period_frac, target=obj.target,
            detail=obj.objective)
        return status

    def transitions(self, name: str,
                    group: Optional[str] = None
                    ) -> List[Tuple[float, str]]:
        state = self._states.get((name, group))
        return list(state.transitions) if state else []

    def publish(self, statuses: Sequence[SloStatus]) -> None:
        if self._burn_gauge is None:
            return
        for s in statuses:
            for wname, b in s.burn.items():
                self._burn_gauge.labels(
                    s.slo_key, f"{wname}_long").set(b["long"])
                self._burn_gauge.labels(
                    s.slo_key, f"{wname}_short").set(b["short"])
            self._budget_gauge.labels(s.slo_key).set(s.budget_remaining)
            self._alert_gauge.labels(s.slo_key).set(
                float(ALERT_LEVELS[s.alert]))


def evaluate_timeline(store: Any, objectives: Sequence[SloObjective],
                      *, times: Optional[Sequence[float]] = None
                      ) -> List[List[SloStatus]]:
    """Replay the stored samples through a fresh engine — the offline
    twin of the live loop: one status list per evaluation instant
    (every stored sample timestamp by default).  ``obs_report --slo``
    and the storm stage's ``slo_report.json`` both render this."""
    engine = SloEngine(objectives)
    if times is None:
        times = sorted({s["t"] for s in getattr(store, "samples", [])})
    return [engine.evaluate(store, now=t) for t in times]


# --------------------------------------------------------- yaml specs
def _parse_scalar(raw: str) -> Any:
    s = raw.strip()
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    if s.lower() in ("null", "~", ""):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s.strip("'\"")


def _parse_yaml_subset(text: str) -> Any:
    """The same discipline as ``ServingConfig.from_yaml``: a
    hand-rolled parser for the indentation subset the spec files use
    (nested maps, lists of maps, scalar values) — no yaml dependency
    in the jax-free report path."""
    lines: List[Tuple[int, str]] = []
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        indent = len(raw) - len(raw.lstrip(" "))
        lines.append((indent, raw.strip()))

    def parse_block(i: int, indent: int) -> Tuple[Any, int]:
        if i >= len(lines):
            return {}, i
        if lines[i][1].startswith("- "):
            items = []
            while i < len(lines) and lines[i][0] == indent \
                    and lines[i][1].startswith("- "):
                head = lines[i][1][2:]
                item_indent = indent + 2
                if ":" in head:
                    k, _, v = head.partition(":")
                    entry: Dict[str, Any] = {}
                    if v.strip():
                        entry[k.strip()] = _parse_scalar(v)
                        i += 1
                    else:
                        i += 1
                        sub, i = parse_block(i, _next_indent(
                            lines, i, item_indent))
                        entry[k.strip()] = sub
                    while i < len(lines) and lines[i][0] >= item_indent \
                            and not lines[i][1].startswith("- "):
                        k2, _, v2 = lines[i][1].partition(":")
                        if v2.strip():
                            entry[k2.strip()] = _parse_scalar(v2)
                            i += 1
                        else:
                            i += 1
                            sub, i = parse_block(i, _next_indent(
                                lines, i, item_indent))
                            entry[k2.strip()] = sub
                    items.append(entry)
                else:
                    items.append(_parse_scalar(head))
                    i += 1
            return items, i
        out: Dict[str, Any] = {}
        while i < len(lines) and lines[i][0] == indent \
                and not lines[i][1].startswith("- "):
            k, _, v = lines[i][1].partition(":")
            if v.strip():
                out[k.strip()] = _parse_scalar(v)
                i += 1
            else:
                i += 1
                if i < len(lines) and lines[i][0] > indent:
                    sub, i = parse_block(i, lines[i][0])
                else:
                    sub = None
                out[k.strip()] = sub
        return out, i

    def _next_indent(ls, i, fallback):
        return ls[i][0] if i < len(ls) else fallback

    doc, _ = parse_block(0, lines[0][0] if lines else 0)
    return doc


def parse_slo_specs(doc: Any) -> List[SloObjective]:
    """Dict/list document -> objectives.  Accepts a bare list or a
    mapping with a ``slos:`` key (so a ``config.yaml`` section and a
    standalone ``slo.yaml`` both work)."""
    if isinstance(doc, dict):
        doc = doc.get("slos") or []
    objectives = []
    for entry in doc or []:
        if not isinstance(entry, dict) or "name" not in entry:
            continue
        windows = []
        for w in entry.get("windows") or []:
            if isinstance(w, dict) and "name" in w:
                windows.append(BurnWindow(
                    str(w["name"]), float(w.get("burn", 14.4)),
                    float(w.get("long_s", 3600.0)),
                    float(w.get("short_s", 300.0))))
        objectives.append(SloObjective(
            name=str(entry["name"]),
            objective=str(entry.get("objective", "availability")),
            target=float(entry.get("target", 0.99)),
            window_s=float(entry.get("window_s", 3600.0)),
            total=entry.get("total"),
            bad=entry.get("bad"),
            good=entry.get("good"),
            histogram=entry.get("histogram"),
            threshold_ms=float(entry.get("threshold_ms", 1000.0)),
            series=entry.get("series"),
            max_age_s=float(entry.get("max_age_s", 60.0)),
            group_by=entry.get("group_by"),
            windows=windows or default_windows(),
            recovery_hold_s=float(entry.get("recovery_hold_s", 0.0))))
    return objectives


def load_slo_yaml(path: str) -> List[SloObjective]:
    with open(path) as f:
        return parse_slo_specs(_parse_yaml_subset(f.read()))
