"""Scrape endpoint: a stdlib ``http.server`` serving the registry in
Prometheus text exposition plus JSON snapshot and Chrome-trace views.

Routes:
    /metrics          Prometheus text exposition 0.0.4 (scrape target);
                      ``?exemplars=1`` appends OpenMetrics-style
                      exemplars (bucket → representative trace_id)
    /metrics.json     registry snapshot as JSON
    /requests.json    the request log's kept timelines (tail-sampled
                      per-request station waterfalls, newest last)
    /tsdb.json        the embedded time-series store's recent samples
                      (``?selector=name{label="v"}``, ``?start=``/
                      ``?end=`` unix seconds filter the answer) — the
                      live query face of ``observability/tsdb.py``
    /metrics/cluster  federated CLUSTER view (host 0 of a multi-host
                      run, when a ClusterAggregator is attached):
                      counters summed across hosts, histograms merged,
                      gauges as per-host vectors, plus the
                      cluster_* skew/straggler gauges
    /metrics/cluster.json  same view as JSON, including the full
                      straggler/collective report under "cluster"
    /trace            Chrome-trace JSON of the span tracer (Perfetto)
    /healthz          liveness ("ok") — or a READINESS probe when the
                      owner installed a ``health_check``: 200 JSON when
                      healthy, 503 JSON naming the reason when not
                      (serving wires its queue-depth / error-rate
                      thresholds in here)

Port 0 binds an ephemeral port (``server.port`` has the real one) —
what tests and multi-worker hosts use to avoid collisions.

Bind host: ``host=None`` resolves ``observability.bind_host`` from the
config (default ``0.0.0.0``).  The endpoint is UNAUTHENTICATED — on a
shared network set ``observability.bind_host 127.0.0.1`` (or a
scrape-only interface) and front it with your scrape proxy.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import unquote as _unquote

from analytics_zoo_tpu.observability.metrics import (
    MetricsRegistry, get_registry)
from analytics_zoo_tpu.observability.tracing import Tracer, get_tracer

log = logging.getLogger("analytics_zoo_tpu.observability")

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    server_version = "zoo-tpu-metrics/1.0"

    def _respond(self, body: bytes, content_type: str,
                 status: int = 200) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path, _, query = self.path.partition("?")
        try:
            if path in ("/metrics", "/"):
                exemplars = "exemplars=1" in query.split("&")
                body = self.server.registry.prometheus_text(
                    exemplars=exemplars).encode()
                self._respond(body, PROM_CONTENT_TYPE)
            elif path == "/metrics.json":
                body = json.dumps(self.server.registry.snapshot(),
                                  indent=2).encode()
                self._respond(body, "application/json")
            elif path == "/requests.json":
                from analytics_zoo_tpu.observability.reqtrace import (
                    get_request_log)
                body = json.dumps(
                    get_request_log().snapshot()).encode()
                self._respond(body, "application/json")
            elif path == "/tsdb.json":
                from analytics_zoo_tpu.observability import tsdb
                writer = tsdb.get_active_tsdb()
                if writer is None:
                    self._respond(
                        b"no tsdb writer active (init_worker_"
                        b"observability starts one inside a run dir)",
                        "text/plain", 404)
                else:
                    params = {}
                    for part in query.split("&"):
                        k, _, v = part.partition("=")
                        if v:
                            params[k] = _unquote(v)
                    store = tsdb.SeriesStore.from_writer(writer)
                    t0, t1 = store.time_range()
                    start = float(params.get("start") or t0 or 0.0)
                    end = float(params.get("end") or t1 or 0.0)
                    sel = params.get("selector")
                    doc = {"start": start, "end": end,
                           "samples": len(store.samples)}
                    if sel:
                        doc["series"] = store.query(sel, start, end)
                    else:
                        # no selector: index answer — the keys
                        # present, not every point
                        doc["counter_keys"] = store.counter_keys("")
                        doc["gauge_keys"] = store.gauge_keys("")
                    body = json.dumps(doc).encode()
                    self._respond(body, "application/json")
            elif path in ("/metrics/cluster", "/metrics/cluster.json"):
                agg = getattr(self.server, "aggregator", None)
                if agg is None:
                    self._respond(
                        b"no cluster aggregator attached (this is a "
                        b"worker endpoint; scrape host 0)",
                        "text/plain", 404)
                elif path.endswith(".json"):
                    body = json.dumps(agg.cluster_snapshot(),
                                      indent=2).encode()
                    self._respond(body, "application/json")
                else:
                    body = agg.prometheus_text().encode()
                    self._respond(body, PROM_CONTENT_TYPE)
            elif path == "/trace":
                body = json.dumps(
                    self.server.tracer.chrome_trace()).encode()
                self._respond(body, "application/json")
            elif path == "/healthz":
                check = getattr(self.server, "health_check", None)
                if check is None:
                    self._respond(b"ok", "text/plain")
                else:
                    try:
                        reason = check()
                    except Exception:
                        log.exception("health check raised")
                        reason = {"reason": "health check raised"}
                    if reason:
                        body = json.dumps(
                            {"ready": False, **reason}).encode()
                        self._respond(body, "application/json", 503)
                    else:
                        self._respond(b'{"ready": true}',
                                      "application/json")
            else:
                self._respond(b"not found", "text/plain", 404)
        except Exception:  # a scrape must never kill the server thread
            log.exception("metrics request failed: %s", self.path)
            try:
                self._respond(b"internal error", "text/plain", 500)
            except Exception:
                pass

    def log_message(self, fmt, *args):  # scrapes are periodic; stay quiet
        log.debug("metrics http: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class MetricsServer:
    """Owns the HTTP listener + its serve thread.  ``start`` is
    idempotent; ``stop`` releases the port."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 health_check=None, aggregator=None):
        if host is None:
            host = default_bind_host()
        self._requested = (host, int(port))
        self.registry = registry if registry is not None \
            else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        # readiness probe: a callable returning None (healthy) or a
        # JSON-able dict naming the reason (-> 503 on /healthz)
        self.health_check = health_check
        # host-0 federation point: a ClusterAggregator serving the
        # /metrics/cluster routes (workers leave this None)
        self.aggregator = aggregator
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        self._httpd = _Server(self._requested, _Handler)
        self._httpd.registry = self.registry
        self._httpd.tracer = self.tracer
        self._httpd.health_check = self.health_check
        self._httpd.aggregator = self.aggregator
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"zoo-metrics-http:{self.port}")
        self._thread.start()
        log.info("metrics endpoint listening on :%d/metrics", self.port)
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None


def default_bind_host() -> str:
    """The configured bind interface (``observability.bind_host``);
    falls back to all interfaces to preserve the historical default."""
    try:
        from analytics_zoo_tpu.common.config import get_config
        return str(get_config().get("observability.bind_host",
                                    "0.0.0.0") or "0.0.0.0")
    except Exception:
        return "0.0.0.0"


def start_metrics_server(port: int = 0, host: Optional[str] = None,
                         registry: Optional[MetricsRegistry] = None,
                         tracer: Optional[Tracer] = None,
                         health_check=None,
                         aggregator=None) -> MetricsServer:
    """Build + start in one call; returns the server (``.port`` holds
    the bound port when ``port=0``)."""
    return MetricsServer(port=port, host=host, registry=registry,
                         tracer=tracer, health_check=health_check,
                         aggregator=aggregator).start()
