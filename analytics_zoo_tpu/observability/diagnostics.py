"""Training-health diagnostics: compile/recompile tracking, XLA
cost-analysis FLOPs, live MFU, and step-time attribution instruments.

PR 1 gave the platform raw instruments (registry, spans, telemetry);
this module *interprets* the signals the way BigDL's driver-side
Metrics table + Spark UI did for the reference: it answers "is the
step slow because of recompilation, input starvation, or the device?"
and "what fraction of peak FLOPs are we getting?".

Three pieces:

* :class:`CompileMonitor` — wraps jitted functions, counts
  compilations (new abstract signatures) and compile seconds per
  function, detects recompilation *churn* after a configurable warmup
  with a loud structured warning naming the offending signature, and
  pulls ``jax.stages`` cost analysis (FLOPs / bytes accessed) into
  gauges so the trainer can publish a live MFU estimate.
* :func:`step_attribution_histogram` — the shared
  ``train_step_time_seconds{component}`` family decomposing each
  wall-clock step into ``data_wait`` (host batch wait), and
  ``host_dispatch`` / ``device`` (dispatch wall vs the sampled
  dispatch→``block_until_ready`` bracket).
* A ``jax.monitoring`` listener accumulating the runtime's own
  ``backend_compile`` durations — the ground-truth compile clock that
  first-call walls (which include the first execution) only bound.

Everything here must degrade to "fewer gauges", never to an exception
on a hot path.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.observability.metrics import (
    MetricsRegistry, get_registry)

log = logging.getLogger("analytics_zoo_tpu.observability")

# Components of one wall-clock training step (the attribution table in
# docs/observability.md "Diagnosing a slow or sick run").
STEP_COMPONENTS = ("data_wait", "host_dispatch", "device")


def step_attribution_histogram(registry: Optional[MetricsRegistry] = None):
    """The shared step-time attribution family; every producer
    (trainer prefetch, DeviceLoader, dispatch bracket) observes into
    the same histogram so ``/metrics`` shows the breakdown directly."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        "train_step_time_seconds",
        "wall-clock step decomposition: data_wait = host wait for the "
        "next device batch; host_dispatch = python + dispatch wall; "
        "device = dispatch->block_until_ready bracket (sampled every "
        "observability.device_time_every steps)",
        labels=("component",))


def _short_signature(sig: Tuple, limit: int = 400) -> str:
    s = repr(sig)
    return s if len(s) <= limit else s[:limit] + "..."


def abstract_signature(args: Tuple) -> Tuple:
    """Shape/dtype key of a call's arguments — the same information a
    jit cache keys on (minus shardings/static args, which the training
    engine holds fixed).  Cheap: no device sync, no tracing."""
    leaves = []
    for a in _tree_leaves(args):
        if a is None:
            leaves.append(None)
        elif hasattr(a, "shape") and hasattr(a, "dtype"):
            leaves.append((tuple(a.shape), str(a.dtype)))
        else:
            # python scalars are weak-typed: the VALUE does not retrace
            # but the TYPE does
            leaves.append(type(a).__name__)
    return tuple(leaves)


def _tree_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(
        tree, is_leaf=lambda v: v is None)


# ----------------------------------------------------------- monitoring
_listener_lock = threading.Lock()
_listener_installed = False


def _backend_compile_listener(event: str, duration: float, **_kw) -> None:
    """jax.monitoring duration listener: accumulate the runtime's own
    compile clocks.  Never raises (it runs inside jax internals)."""
    try:
        if "compile" not in event:
            return
        reg = get_registry()
        if event.endswith("backend_compile_duration"):
            reg.counter(
                "jax_backend_compiles_total",
                "XLA backend compilations (jax.monitoring)").inc()
            reg.counter(
                "jax_backend_compile_seconds_total",
                "seconds inside XLA backend_compile "
                "(jax.monitoring)").inc(float(duration))
    except Exception:
        pass


def install_compile_listener() -> bool:
    """Register the ``jax.monitoring`` compile-duration listener once
    per process; returns whether the hook is active."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(
                _backend_compile_listener)
            _listener_installed = True
        except Exception:
            return False
    return True


# -------------------------------------------------------- CompileMonitor
class _MonitoredJit:
    """A jitted callable wrapped with per-signature compile tracking.

    Warmup/churn state lives on the WRAPPER (one per built program),
    so a freshly built trainer starts a fresh warmup; the metrics it
    feeds aggregate per function *name* in the shared registry.
    Unknown attributes (``lower``, ``trace``, ...) forward to the
    underlying jitted function, so AOT helpers like
    ``benchmarks.compiled_flops`` keep working on the wrapped object.
    """

    # after this many consecutive same-signature checks the wrapper is
    # "stable" and only every CHECK_EVERY-th call pays the signature
    # walk — per-step churn is still caught at the sampled calls, and
    # the hot path stops paying a whole-pytree walk (params can be
    # thousands of leaves) on every dispatch
    STABLE_STREAK = 32
    CHECK_EVERY = 8

    def __init__(self, monitor: "CompileMonitor", name: str, fn):
        self._monitor = monitor
        self._name = name
        self._fn = fn
        self._signatures: set = set()
        self._calls = 0
        self._stable_streak = 0

    def __call__(self, *args):
        mon, name = self._monitor, self._name
        check = (self._stable_streak < self.STABLE_STREAK
                 or self._calls % self.CHECK_EVERY == 0)
        is_new = False
        key = None
        if check:
            try:
                key = abstract_signature(args)
                is_new = key not in self._signatures
            except Exception:
                key, is_new = None, False
        t0 = time.perf_counter()
        out = self._fn(*args)
        if is_new:
            self._signatures.add(key)
            self._stable_streak = 0
            mon._record_compile(
                name, key, time.perf_counter() - t0,
                calls_before=self._calls,
                warmed_up=self._calls >= mon.warmup_calls,
                n_signatures=len(self._signatures))
            mon._maybe_cost_analysis(name, self._fn, args)
        elif check:
            self._stable_streak += 1
        self._calls += 1
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


class CompileMonitor:
    """Per-function compile accounting over the shared registry.

    ``wrap(name, jitted)`` returns a transparent callable; each call
    whose abstract signature (arg shapes/dtypes) was not seen by that
    wrapper counts as a compilation.  Signatures appearing after
    ``warmup_calls`` calls are *recompilation churn* — the classic
    silent TPU perf killer (a shape/dtype drifting per step recompiles
    every step) — and emit one loud structured warning each, naming
    the offending abstract signature.

    First-call wall time is recorded as ``jax_compile_seconds_total``
    (an upper bound: it includes the first execution); the
    ``jax.monitoring`` listener records the runtime's own
    ``backend_compile`` seconds alongside.
    """

    def __init__(self, warmup_calls: Optional[int] = None,
                 cost_analysis: Optional[bool] = None,
                 registry: Optional[MetricsRegistry] = None):
        if warmup_calls is None or cost_analysis is None:
            try:
                from analytics_zoo_tpu.common.config import get_config
                cfg = get_config()
                if warmup_calls is None:
                    warmup_calls = int(cfg.get(
                        "observability.compile_warmup_calls", 3))
                if cost_analysis is None:
                    cost_analysis = bool(cfg.get(
                        "observability.cost_analysis", True))
            except Exception:
                warmup_calls = 3 if warmup_calls is None else warmup_calls
                cost_analysis = True if cost_analysis is None \
                    else cost_analysis
        self.warmup_calls = int(warmup_calls)
        self.cost_analysis = bool(cost_analysis)
        self._registry = registry
        self._lock = threading.Lock()
        # per-name aggregates (across wrapper instances)
        self._stats: Dict[str, Dict[str, float]] = {}
        install_compile_listener()

    def _reg(self) -> MetricsRegistry:
        # lazy: survives reset_registry() between tests/runs
        return self._registry if self._registry is not None \
            else get_registry()

    # ------------------------------------------------------------- wrap
    def wrap(self, name: str, jitted) -> _MonitoredJit:
        return _MonitoredJit(self, name, jitted)

    def _state(self, name: str) -> Dict[str, float]:
        st = self._stats.get(name)
        if st is None:
            st = self._stats.setdefault(name, {
                "compiles": 0, "recompiles_after_warmup": 0,
                "compile_seconds": 0.0, "flops": None, "bytes": None,
            })
        return st

    def _record_compile(self, name: str, key, wall_s: float,
                        calls_before: int, warmed_up: bool,
                        n_signatures: int) -> None:
        reg = self._reg()
        with self._lock:
            st = self._state(name)
            st["compiles"] += 1
            st["compile_seconds"] += wall_s
            if warmed_up:
                st["recompiles_after_warmup"] += 1
        reg.counter(
            "jax_compiles_total",
            "jit compilations observed per monitored function (new "
            "abstract signatures)", labels=("fn",)).labels(name).inc()
        reg.counter(
            "jax_compile_seconds_total",
            "first-call wall seconds per new signature (upper bound "
            "on compile time; includes the first execution)",
            labels=("fn",)).labels(name).inc(wall_s)
        if warmed_up:
            reg.counter(
                "jax_recompiles_total",
                "compilations AFTER the warmup — recompilation churn",
                labels=("fn",)).labels(name).inc()
            log.warning(
                "recompilation churn: %r compiled signature #%d on "
                "call %d (after its %d-call warmup), %.2fs — a "
                "shape/dtype is drifting between steps; offending "
                "abstract signature: %s",
                name, n_signatures, calls_before + 1,
                self.warmup_calls, wall_s, _short_signature(key))
        else:
            log.info("compiled %r signature #%d in %.2fs (call %d)",
                     name, n_signatures, wall_s, calls_before + 1)

    # ----------------------------------------------- executable cache
    def record_cache_event(self, name: str, hit: bool,
                           seconds: Optional[float] = None) -> None:
        """Persistent-executable-cache accounting (fed by
        ``compile.engine.EngineJit``): hits/misses per function plus
        the deserialize wall on hits — the cold-vs-warm evidence
        ``obs_report`` renders as the cache-effectiveness line.  A hit
        replaces an XLA compile (141s for ResNet-50, BENCH_r05) with a
        ~seconds load, so ``compile_cache_load_seconds`` vs
        ``jax_compile_seconds_total`` IS the warm-start win."""
        reg = self._reg()
        with self._lock:
            st = self._state(name)
            st["cache_hits"] = st.get("cache_hits", 0) + (1 if hit else 0)
            st["cache_misses"] = st.get("cache_misses", 0) + \
                (0 if hit else 1)
            if hit and seconds is not None:
                st["cache_load_seconds"] = \
                    st.get("cache_load_seconds", 0.0) + seconds
        if hit:
            reg.counter(
                "compile_cache_hits_total",
                "persistent executable-cache hits (deserialized "
                "instead of compiled)", labels=("fn",)).labels(name).inc()
            if seconds is not None:
                reg.counter(
                    "compile_cache_load_seconds",
                    "seconds spent deserializing cached executables "
                    "(the warm-start cost that replaces a full XLA "
                    "compile)", labels=("fn",)).labels(name).inc(seconds)
        else:
            reg.counter(
                "compile_cache_misses_total",
                "persistent executable-cache misses (full XLA compile "
                "paid)", labels=("fn",)).labels(name).inc()

    # ---------------------------------------------------- cost analysis
    def _maybe_cost_analysis(self, name: str, fn, args) -> None:
        """FLOPs / bytes of the just-compiled program into gauges.

        Prefers ``Lowered.cost_analysis()`` (pure HLO analysis — no
        second backend compile); falls back to compiling the lowered
        program (``jax.stages.Compiled.cost_analysis()``), which recent
        runtimes dedupe via the compilation cache.  Lowering uses
        ShapeDtypeStructs built *before* the call, so donated/deleted
        buffers are never touched."""
        if not self.cost_analysis:
            return
        try:
            import jax

            def sds(a):
                if a is None:
                    return None
                if hasattr(a, "shape") and hasattr(a, "dtype"):
                    return jax.ShapeDtypeStruct(
                        tuple(a.shape), np.dtype(a.dtype))
                return a   # python scalar: pass through
            shaped = jax.tree_util.tree_map(
                sds, args, is_leaf=lambda v: v is None)
            lowered = fn.lower(*shaped)
            try:
                cost = lowered.cost_analysis()
            except Exception:
                cost = lowered.compile().cost_analysis()
            if isinstance(cost, list):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0)) or None
            hbm = float(cost.get("bytes accessed", 0.0)) or None
        except Exception:
            return
        reg = self._reg()
        with self._lock:
            st = self._state(name)
            st["flops"], st["bytes"] = flops, hbm
        if flops is not None:
            reg.gauge(
                "train_step_flops",
                "XLA cost-analysis FLOPs of the compiled program "
                "(scan bodies counted once)", labels=("fn",)
            ).labels(name).set(flops)
        if hbm is not None:
            reg.gauge(
                "train_step_hbm_bytes",
                "XLA cost-analysis bytes accessed of the compiled "
                "program", labels=("fn",)).labels(name).set(hbm)

    # ------------------------------------------------------------ reads
    def flops(self, name: str) -> Optional[float]:
        with self._lock:
            st = self._stats.get(name)
            return st["flops"] if st else None

    def stats(self, name: Optional[str] = None) -> Dict[str, Any]:
        """Per-function aggregates (or all of them) — what
        ``scripts/check_determinism.py`` asserts on."""
        with self._lock:
            if name is not None:
                return dict(self._stats.get(name, {}))
            return {k: dict(v) for k, v in self._stats.items()}


_global_monitor: Optional[CompileMonitor] = None
_monitor_lock = threading.Lock()


def get_compile_monitor() -> CompileMonitor:
    """The process-wide monitor the training engines wrap through."""
    global _global_monitor
    if _global_monitor is None:
        with _monitor_lock:
            if _global_monitor is None:
                _global_monitor = CompileMonitor()
    return _global_monitor


def reset_compile_monitor() -> None:
    """Drop the process-wide monitor (test helper)."""
    global _global_monitor
    with _monitor_lock:
        _global_monitor = None


# ----------------------------------------------------------------- MFU
def publish_mfu(fn_name: str, device_step_s: float,
                registry: Optional[MetricsRegistry] = None
                ) -> Optional[float]:
    """Set the live ``train_mfu`` gauge from the monitored function's
    cost-analysis FLOPs and a sampled device step time.

    The denominator is the chip's bf16 peak (``benchmarks.PEAK_FLOPS``
    by device kind) or the ``observability.peak_flops`` override —
    required on backends whose peak is unknown (CPU).  Returns the MFU
    or None when it cannot be computed (the gauge then keeps its last
    value; it exists at 0 from registration)."""
    reg = registry if registry is not None else get_registry()
    gauge = reg.gauge(
        "train_mfu",
        "model FLOPs utilisation: cost-analysis FLOPs / sampled device "
        "step time / chip peak (observability.peak_flops overrides the "
        "denominator)")
    try:
        flops = get_compile_monitor().flops(fn_name)
        if not flops or device_step_s <= 0:
            return None
        peak = None
        try:
            from analytics_zoo_tpu.common.config import get_config
            peak = float(get_config().get(
                "observability.peak_flops", 0.0)) or None
        except Exception:
            peak = None
        import jax
        from analytics_zoo_tpu.benchmarks import mfu_estimate
        mfu = mfu_estimate(flops, device_step_s, jax.devices()[0],
                           peak=peak)
        if mfu is not None:
            gauge.set(mfu)
        return mfu
    except Exception:
        return None
