"""Incident forensics engine: run-dir artifacts -> named root cause.

The flight recorder (``flightrec.py``) leaves journals; heartbeats,
``degraded.json``, blackboxes, SLO reports and request waterfalls are
already on disk.  This module is the *join*: it folds a finished run
dir's evidence into one causally-ordered incident timeline and runs a
rule-based diagnoser table over it, producing a **ranked root-cause
hypothesis list with evidence citations** — each citation names the
concrete event id (``host-0/e12``), trace id, or heartbeat gap that
supports the claim — emitted as ``incident.json``.

The diagnoser is a table, not a model: each rule is a plain function
``(ctx) -> hypothesis | None`` whose confidence arithmetic is written
out in the open (docs/observability.md reproduces the table).  Rules
distinguish cause from symptom — a fleet-wide breaker-open right
after a ``serving.redis`` chaos trip is a broker outage, and the
replica restarts that follow are *symptoms*, listed under the
hypothesis rather than competing with it.

Surfaces: ``scripts/zoo-doctor RUN_DIR`` (exit code = whether a root
cause was identified) and ``obs_report --incident``.

CONTRACT: stdlib-only at module level, loadable by file path (the
``aggregator.py`` contract) — sibling modules (``flightrec.py``,
``tsdb.py``) are path-loaded the same way, so the whole forensics
stack renders dead run dirs on a jax-free control node.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "INCIDENT_SCHEMA",
    "INCIDENT_FILENAME",
    "ROOT_CAUSE_THRESHOLD",
    "RULES",
    "diagnose",
    "gather",
    "render_incident",
    "write_incident",
]

INCIDENT_SCHEMA = 1
INCIDENT_FILENAME = "incident.json"

# a hypothesis at or above this confidence counts as "root cause
# identified" — zoo-doctor's exit code contract
ROOT_CAUSE_THRESHOLD = 0.6

HEARTBEAT_FILE = "heartbeat.json"     # local twin of detector.py
CLUSTER_FILE = "cluster.json"         # local twin of aggregator.py
DEGRADED_FILE = "degraded.json"
BLACKBOX_FILE = "blackbox.json"
REQUESTS_FILE = "requests.json"
SLO_REPORT_FILE = "slo_report.json"


# ------------------------------------------------------ sibling loads
def _load_sibling(name: str):
    """Path-load a sibling observability module (``flightrec``,
    ``tsdb``): this module is itself path-loaded by zoo-doctor where
    the package may not be importable, so package imports are out."""
    import importlib.util
    import sys
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"{name}.py")
    modname = f"_zoo_{name}_offline"
    if modname in sys.modules:
        return sys.modules[modname]
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: dataclass field-annotation resolution
    # looks the defining module up in sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _read_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# ------------------------------------------------------------- gather
def gather(run_dir: str, *, stale_after_s: float = 10.0) -> Dict[str, Any]:
    """Collect every forensic artifact a run dir offers into one
    context dict — the diagnoser rules' working set.  Every source is
    optional: a half-written run dir still gathers (that is the
    point — the run *died*)."""
    flightrec = _load_sibling("flightrec")
    events = flightrec.read_events(run_dir)
    journals = flightrec.journal_paths(run_dir)
    torn = []
    for stream, path in journals:
        parsed = flightrec.read_journal(path)
        if parsed["torn_tail"]:
            torn.append(stream)

    cluster = _read_json(os.path.join(run_dir, CLUSTER_FILE))
    degraded = _read_json(os.path.join(run_dir, DEGRADED_FILE))
    supervisor = _read_json(os.path.join(run_dir, "supervisor.json"))
    respawns = _read_json(os.path.join(run_dir, "job", "respawns.json"))

    heartbeats: Dict[str, Dict[str, Any]] = {}
    blackboxes: Dict[str, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        names = []
    for name in names:
        if not name.startswith("host-"):
            continue
        hb = _read_json(os.path.join(run_dir, name, HEARTBEAT_FILE))
        if isinstance(hb, dict):
            heartbeats[name] = hb
        bb = _read_json(os.path.join(run_dir, name, BLACKBOX_FILE))
        if isinstance(bb, dict):
            blackboxes[name] = bb
    if not blackboxes:
        bb = _read_json(os.path.join(run_dir, BLACKBOX_FILE))
        if isinstance(bb, dict):
            blackboxes["run"] = bb

    # the run's activity horizon: latest event / beat / blackbox —
    # heartbeat gaps are measured against it, never wall-now, so a
    # week-old run dir diagnoses identically
    t_end = 0.0
    t_start = None
    for ev in events:
        t_end = max(t_end, float(ev.get("t", 0.0)))
        t = float(ev.get("t", 0.0))
        t_start = t if t_start is None else min(t_start, t)
    for hb in heartbeats.values():
        t_end = max(t_end, float(hb.get("time", 0.0)))
    for bb in blackboxes.values():
        t_end = max(t_end, float(bb.get("written", 0.0)))

    gaps: List[Dict[str, Any]] = []
    for host, hb in sorted(heartbeats.items()):
        beat = float(hb.get("time", 0.0))
        gap = t_end - beat
        if gap > stale_after_s:
            gaps.append({"host": host, "last_beat": beat,
                         "gap_s": round(gap, 3),
                         "step": hb.get("step"),
                         "process_index": hb.get("process_index")})

    # request waterfalls: non-ok trace ids are the citation currency
    # joining serving incidents to client-observed failures
    bad_traces: List[Dict[str, Any]] = []
    req_docs = []
    doc = _read_json(os.path.join(run_dir, REQUESTS_FILE))
    if isinstance(doc, dict):
        req_docs.append(doc)
    for name in names:
        if name.startswith("host-"):
            doc = _read_json(os.path.join(run_dir, name, REQUESTS_FILE))
            if isinstance(doc, dict):
                req_docs.append(doc)
    for doc in req_docs:
        for tl in (doc.get("timelines") or []):
            if not isinstance(tl, dict):
                continue
            outcome = tl.get("outcome", "ok")
            if outcome not in ("ok", "pending"):
                bad_traces.append({
                    "trace_id": tl.get("trace_id"),
                    "outcome": outcome})
    bad_traces = bad_traces[:32]

    # SLO alert transitions (loadtest's slo_report.json) — accepted in
    # both shapes the engine has emitted: [[t, level], ...] pairs or
    # {"t":, "alert":} dicts
    slo_transitions: List[Dict[str, Any]] = []
    slo_doc = _read_json(os.path.join(run_dir, SLO_REPORT_FILE))

    def _walk_slo(node: Any, name: str) -> None:
        if isinstance(node, dict):
            nm = node.get("name", name)
            for k, v in node.items():
                if k == "transitions" and isinstance(v, list):
                    for tr in v:
                        if isinstance(tr, (list, tuple)) and len(tr) == 2:
                            slo_transitions.append(
                                {"objective": nm, "t": float(tr[0]),
                                 "alert": str(tr[1])})
                        elif isinstance(tr, dict) and "t" in tr:
                            slo_transitions.append(
                                {"objective": nm, "t": float(tr["t"]),
                                 "alert": str(tr.get("alert",
                                                     tr.get("state", "?")))})
                else:
                    _walk_slo(v, nm)
        elif isinstance(node, list):
            for item in node:
                _walk_slo(item, name)

    if slo_doc is not None:
        _walk_slo(slo_doc, "slo")

    # tsdb corroboration: the serving breaker gauge's open intervals
    # (independent of the event journal — a worker whose journal was
    # lost still shows up here)
    tsdb_breaker_opens: List[Dict[str, Any]] = []
    try:
        tsdb = _load_sibling("tsdb")
        store = tsdb.SeriesStore.from_run_dir(run_dir)
        for key, pts in store.gauge_points("serving_breaker_state").items():
            prev = 0.0
            for t, v in pts:
                if v >= 2.0 > prev:
                    tsdb_breaker_opens.append(
                        {"series": key, "t": float(t)})
                prev = v
    except Exception:   # noqa: BLE001 — corroboration only
        pass

    return {
        "run_dir": run_dir,
        "events": events,
        "journals": [{"stream": s, "path": p} for s, p in journals],
        "torn_streams": torn,
        "cluster": cluster,
        "degraded": degraded,
        "supervisor": supervisor,
        "respawns": respawns,
        "heartbeats": heartbeats,
        "heartbeat_gaps": gaps,
        "blackboxes": blackboxes,
        "bad_traces": bad_traces,
        "slo_transitions": slo_transitions,
        "tsdb_breaker_opens": tsdb_breaker_opens,
        "t_start": t_start,
        "t_end": t_end or None,
        "stale_after_s": stale_after_s,
    }


# ----------------------------------------------------------- timeline
def _event_summary(ev: Dict[str, Any]) -> str:
    d = ev.get("d") or {}
    bits = ", ".join(f"{k}={d[k]}" for k in sorted(d))
    return f"{ev.get('kind')}({bits})" if bits else str(ev.get("kind"))


def build_timeline(ctx: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Fold events + derived observations into one causally-ordered
    timeline.  Event ``t`` values are wall clock; per-session clock
    anchors (the PR 4 launcher contract, carried in journal headers
    and ``meta.json``) make cross-host ordering honest on multi-host
    fleets sharing an anchor."""
    entries: List[Dict[str, Any]] = []
    for ev in ctx["events"]:
        entries.append({
            "t": float(ev.get("t", 0.0)),
            "id": ev.get("id"),
            "src": ev.get("stream"),
            "kind": ev.get("kind"),
            "summary": _event_summary(ev),
            "detail": ev.get("d") or {},
        })
    for gap in ctx["heartbeat_gaps"]:
        entries.append({
            "t": float(gap["last_beat"]),
            "id": f"heartbeat:{gap['host']}",
            "src": gap["host"],
            "kind": "heartbeat.gap",
            "summary": (f"last heartbeat of {gap['host']} "
                        f"({gap['gap_s']}s before the run's end)"),
            "detail": gap,
        })
    for host, bb in sorted(ctx["blackboxes"].items()):
        entries.append({
            "t": float(bb.get("written", 0.0)),
            "id": f"blackbox:{host}",
            "src": host,
            "kind": "blackbox.written",
            "summary": (f"blackbox dump ({bb.get('reason')}) "
                        f"with {len(bb.get('events') or [])} ring events"),
            "detail": {"reason": bb.get("reason"),
                       "error": bb.get("error")},
        })
    for tr in ctx["slo_transitions"]:
        entries.append({
            "t": float(tr["t"]),
            "id": f"slo:{tr['objective']}",
            "src": "slo_report",
            "kind": "slo.transition",
            "summary": (f"SLO {tr['objective']} -> {tr['alert']}"),
            "detail": tr,
        })
    for opn in ctx["tsdb_breaker_opens"]:
        entries.append({
            "t": float(opn["t"]),
            "id": f"tsdb:{opn['series']}",
            "src": "tsdb",
            "kind": "tsdb.breaker_open",
            "summary": f"tsdb gauge {opn['series']} reached open",
            "detail": opn,
        })
    if ctx["degraded"] is not None:
        deg = ctx["degraded"]
        path = os.path.join(ctx["run_dir"], DEGRADED_FILE)
        try:
            t = os.path.getmtime(path)
        except OSError:
            t = ctx["t_end"] or 0.0
        entries.append({
            "t": float(t),
            "id": "degraded.json",
            "src": "run",
            "kind": "degraded.record",
            "summary": (f"degraded: {deg.get('component', '?')} — "
                        f"{deg.get('reason', '?')}"),
            "detail": {k: deg.get(k) for k in
                       ("component", "reason", "classification",
                        "exit_code") if k in deg},
        })
    entries.sort(key=lambda e: (e["t"], str(e["id"])))
    return entries


# ---------------------------------------------------------- diagnoser
def _ev(ctx: Dict[str, Any], kind: str,
        pred: Optional[Callable[[Dict], bool]] = None
        ) -> List[Dict[str, Any]]:
    out = []
    for ev in ctx["events"]:
        if ev.get("kind") != kind:
            continue
        if pred is not None and not pred(ev.get("d") or {}):
            continue
        out.append(ev)
    return out


def _cite(ev: Dict[str, Any], note: str) -> Dict[str, Any]:
    return {"ref": ev.get("id"), "t": float(ev.get("t", 0.0)),
            "note": note}


def rule_broker_outage(ctx: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Fleet-wide breaker-open (± a ``serving.redis`` chaos trip, dead
    letters, tsdb corroboration) -> the broker died; restarts and
    failed requests that follow are symptoms."""
    opens = _ev(ctx, "breaker.transition",
                lambda d: str(d.get("to")) == "open")
    redis_trips = _ev(ctx, "chaos.trip",
                      lambda d: "redis" in str(d.get("site", "")))
    if not opens and not redis_trips:
        return None
    evidence = [
        _cite(ev, f"breaker opened on {ev.get('stream')} "
                  f"(failures={((ev.get('d') or {}).get('failures', '?'))})")
        for ev in opens[:6]]
    conf = 0.55 if opens else 0.30
    streams = {ev.get("stream") for ev in opens}
    if len(streams) > 1:
        conf += 0.10   # fleet-wide, not one sick replica
    first_open = min((float(e.get("t", 0.0)) for e in opens),
                     default=None)
    for trip in redis_trips:
        t = float(trip.get("t", 0.0))
        if first_open is None or abs(first_open - t) <= 5.0:
            conf += 0.25
            evidence.append(_cite(
                trip, "chaos fault fired at the broker site "
                      f"({(trip.get('d') or {}).get('site')})"))
            break
    letters = _ev(ctx, "dead_letter",
                  lambda d: d.get("reason") == "write_abandoned")
    if letters:
        conf += 0.05
        evidence.append(_cite(
            letters[0],
            f"result write abandoned ({len(letters)} dead letter(s))"))
    if ctx["tsdb_breaker_opens"]:
        conf += 0.02
        opn = ctx["tsdb_breaker_opens"][0]
        evidence.append({"ref": f"tsdb:{opn['series']}",
                         "t": opn["t"],
                         "note": "tsdb breaker gauge corroborates"})
    symptoms = []
    if first_open is not None:
        for ev in (_ev(ctx, "replica.exit") + _ev(ctx, "replica.spawn")):
            if float(ev.get("t", 0.0)) >= first_open:
                symptoms.append(ev.get("id"))
    return {
        "cause": "broker_outage",
        "title": "broker (redis transport) outage",
        "confidence": round(min(conf, 0.97), 3),
        "evidence": evidence,
        "symptoms": sorted(symptoms)[:12],
        "explanation": (
            "circuit breakers opened"
            + (" fleet-wide" if len(streams) > 1 else "")
            + (" within seconds of a chaos fault at the broker site"
               if redis_trips else "")
            + "; replica restarts and request failures after the first "
              "open are symptoms of the dead broker, not causes."),
    }


def rule_poison_record(ctx: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """A quarantined record (± poison dead letters, worker deaths per
    delivery) -> one input kept killing its worker."""
    quarantines = _ev(ctx, "quarantine")
    poison_letters = _ev(ctx, "dead_letter",
                         lambda d: d.get("reason") == "poison")
    if not quarantines and not poison_letters:
        return None
    evidence = []
    conf = 0.80 if quarantines else 0.45
    for q in quarantines[:4]:
        d = q.get("d") or {}
        evidence.append(_cite(
            q, "record quarantined after "
               f"{d.get('deliveries', '?')} deliveries "
               f"(request_id={d.get('request_id', '?')})"))
    for pl in poison_letters[:2]:
        evidence.append(_cite(pl, "poison dead letter"))
    # worker deaths between deliveries are the kill mechanism
    kills = _ev(ctx, "replica.exit",
                lambda d: str(d.get("classification", ""))
                .startswith("signal")
                or str(d.get("classification", "")).startswith("error"))
    if quarantines and kills:
        conf += 0.10
        evidence.append(_cite(
            kills[0], f"replica death per delivery "
                      f"({len(kills)} exit(s) recorded)"))
    bad = {b.get("trace_id") for b in ctx["bad_traces"]}
    cited_req = {str((q.get("d") or {}).get("request_id"))
                 for q in quarantines}
    joined = sorted(t for t in bad & cited_req if t)
    if joined:
        conf += 0.05
        evidence.append({"ref": f"trace:{joined[0]}", "t": None,
                         "note": "client-side waterfall shows the "
                                 "same request failing"})
    return {
        "cause": "poison_record",
        "title": "poison record repeatedly killing its worker",
        "confidence": round(min(conf, 0.97), 3),
        "evidence": evidence,
        "symptoms": sorted(e.get("id") for e in kills)[:12],
        "explanation": (
            "one record reached the per-record delivery cap and was "
            "quarantined to the dead-letter stream; the replica deaths "
            "before the quarantine are its kill mechanism, not an "
            "independent fleet problem."),
    }


def rule_lost_host(ctx: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Lost-host failure class / mesh reformation / heartbeat gap ->
    a worker host vanished (preemption, kill)."""
    lost = _ev(ctx, "train.failure",
               lambda d: str(d.get("classification")) == "lost_host")
    reforms = _ev(ctx, "mesh.reform")
    gaps = ctx["heartbeat_gaps"]
    kill_trips = _ev(ctx, "chaos.trip",
                     lambda d: str(d.get("kind")) in
                     ("lose_host", "kill"))
    steals = _ev(ctx, "lease.steal")
    if not (lost or reforms or gaps or kill_trips):
        return None
    conf = 0.0
    evidence = []
    if lost:
        conf += 0.60
        evidence.extend(_cite(
            ev, "step failure classified lost_host "
                f"({(ev.get('d') or {}).get('error', '')})".strip())
            for ev in lost[:3])
    if reforms:
        conf += 0.20 if lost else 0.50
        d = reforms[0].get("d") or {}
        evidence.append(_cite(
            reforms[0],
            f"mesh re-formed on the survivors "
            f"({d.get('old_devices', '?')} -> "
            f"{d.get('new_devices', '?')} devices)"))
    if gaps:
        conf += 0.10
        g = gaps[0]
        evidence.append({
            "ref": f"heartbeat:{g['host']}", "t": g["last_beat"],
            "note": f"{g['host']} heartbeat went silent "
                    f"{g['gap_s']}s before the run's end"})
    if kill_trips:
        conf += 0.10
        evidence.append(_cite(
            kill_trips[0], "chaos fault of the host-loss kind fired "
                           f"({(kill_trips[0].get('d') or {}).get('site')})"))
    if steals and not (lost or reforms):
        conf += 0.10
        evidence.append(_cite(
            steals[0], "expired shard lease stolen from the dead "
                       "owner (recompute debt paid)"))
    return {
        "cause": "lost_host",
        "title": "lost worker host (preemption / kill)",
        "confidence": round(min(conf, 0.97), 3),
        "evidence": evidence,
        "symptoms": sorted(e.get("id") for e in
                           _ev(ctx, "worker.respawn"))[:12],
        "explanation": (
            "a worker host disappeared mid-run; the mesh reformation / "
            "lease steals / respawns that follow are the platform "
            "absorbing the loss, not independent failures."),
    }


def rule_training_numerics(ctx: Dict[str, Any]
                           ) -> Optional[Dict[str, Any]]:
    """Watchdog nonfinite/divergence episodes -> training numerics
    (bad step, data, or LR), not infrastructure."""
    hard = _ev(ctx, "watchdog.episode",
               lambda d: str(d.get("issue")) in
               ("nonfinite", "divergence"))
    soft = _ev(ctx, "watchdog.episode",
               lambda d: str(d.get("issue")) in
               ("plateau", "stall", "drift"))
    if not hard and not soft:
        return None
    conf = 0.65 if hard else 0.40
    evidence = [_cite(ev, f"watchdog flagged "
                          f"{(ev.get('d') or {}).get('issue')}")
                for ev in (hard or soft)[:4]]
    if hard and _ev(ctx, "train.degraded"):
        conf += 0.10
        evidence.append(_cite(_ev(ctx, "train.degraded")[0],
                              "the run degraded after the episode"))
    return {
        "cause": "training_numerics",
        "title": "training numerics episode (watchdog)",
        "confidence": round(min(conf, 0.97), 3),
        "evidence": evidence,
        "symptoms": [],
        "explanation": (
            "the training watchdog flagged numerics-level episodes; "
            "infrastructure looks healthy around them."),
    }


def rule_budget_exhausted(ctx: Dict[str, Any]
                          ) -> Optional[Dict[str, Any]]:
    """A degraded record / fleet.degraded event with no stronger
    cause: the restart budget drained.  Deliberately mid-confidence —
    when a real cause (outage, poison, lost host) exists, its rule
    outranks this one and the degradation is the symptom."""
    deg = ctx["degraded"]
    deg_events = _ev(ctx, "fleet.degraded") + _ev(ctx, "train.degraded")
    if deg is None and not deg_events:
        return None
    evidence = []
    if deg is not None:
        evidence.append({
            "ref": "degraded.json", "t": None,
            "note": f"{deg.get('component', '?')}: "
                    f"{deg.get('reason', '?')} "
                    f"(classification="
                    f"{deg.get('classification', '?')})"})
    evidence.extend(_cite(ev, "degradation recorded")
                    for ev in deg_events[:2])
    return {
        "cause": "restart_budget_exhausted",
        "title": "restart budget exhausted (degraded exit)",
        "confidence": 0.50,
        "evidence": evidence,
        "symptoms": [],
        "explanation": (
            "the run ended through the degraded path; if another "
            "hypothesis ranks above this one, the budget drain is that "
            "cause's symptom."),
    }


RULES: List[Tuple[str, Callable[[Dict[str, Any]],
                                Optional[Dict[str, Any]]]]] = [
    ("broker_outage", rule_broker_outage),
    ("poison_record", rule_poison_record),
    ("lost_host", rule_lost_host),
    ("training_numerics", rule_training_numerics),
    ("restart_budget_exhausted", rule_budget_exhausted),
]


# ------------------------------------------------------------ diagnose
def diagnose(run_dir: str, *,
             stale_after_s: float = 10.0,
             max_timeline: int = 400) -> Dict[str, Any]:
    """Gather, join, diagnose: the whole engine in one call.  Returns
    the ``incident.json`` document (not yet written)."""
    ctx = gather(run_dir, stale_after_s=stale_after_s)
    timeline = build_timeline(ctx)
    hypotheses = []
    for _name, rule in RULES:
        try:
            hyp = rule(ctx)
        except Exception:   # noqa: BLE001 — one bad rule, not the report
            hyp = None
        if hyp is not None:
            hypotheses.append(hyp)
    hypotheses.sort(key=lambda h: (-h["confidence"], h["cause"]))
    for rank, hyp in enumerate(hypotheses, start=1):
        hyp["rank"] = rank
    identified = bool(hypotheses) and \
        hypotheses[0]["confidence"] >= ROOT_CAUSE_THRESHOLD
    truncated = max(0, len(timeline) - max_timeline)
    return {
        "incident_schema": INCIDENT_SCHEMA,
        "run_dir": os.path.abspath(run_dir),
        "generated_unix": time.time(),
        "window": {"t_start": ctx["t_start"], "t_end": ctx["t_end"]},
        "identified": identified,
        "root_cause": hypotheses[0]["cause"] if identified else None,
        "hypotheses": hypotheses,
        "timeline": timeline[-max_timeline:],
        "timeline_truncated": truncated,
        "artifacts": {
            "journals": [j["stream"] for j in ctx["journals"]],
            "torn_streams": ctx["torn_streams"],
            "events": len(ctx["events"]),
            "heartbeats": len(ctx["heartbeats"]),
            "heartbeat_gaps": len(ctx["heartbeat_gaps"]),
            "blackboxes": sorted(ctx["blackboxes"]),
            "degraded": ctx["degraded"] is not None,
            "supervisor_log": ctx["supervisor"] is not None,
            "respawn_log": ctx["respawns"] is not None,
            "slo_transitions": len(ctx["slo_transitions"]),
            "bad_traces": len(ctx["bad_traces"]),
        },
    }


def write_incident(run_dir: str, out_path: Optional[str] = None,
                   **kw: Any) -> Tuple[Dict[str, Any], str]:
    """Diagnose and persist ``incident.json`` (atomic
    write-then-rename, like every other run-dir artifact)."""
    doc = diagnose(run_dir, **kw)
    path = out_path or os.path.join(run_dir, INCIDENT_FILENAME)
    # hand-rolled atomic write: stdlib-only file-path-loadable module
    # (zoo-doctor), so it cannot import common.fsutil
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return doc, path


# -------------------------------------------------------------- render
def render_incident(doc: Dict[str, Any], *,
                    timeline_tail: int = 20) -> str:
    """Human-readable report — shared by ``zoo-doctor`` and
    ``obs_report --incident``."""
    lines: List[str] = []
    arts = doc.get("artifacts", {})
    lines.append("== Incident report ==")
    lines.append(f"run dir: {doc.get('run_dir')}")
    lines.append(
        "evidence: "
        f"{arts.get('events', 0)} event(s) across "
        f"{len(arts.get('journals', []))} journal(s)"
        + (f" (torn tail: {', '.join(arts['torn_streams'])})"
           if arts.get("torn_streams") else "")
        + f", {arts.get('heartbeats', 0)} heartbeat(s) "
        f"({arts.get('heartbeat_gaps', 0)} gap(s)), "
        f"{len(arts.get('blackboxes', []))} blackbox(es), "
        f"degraded={'yes' if arts.get('degraded') else 'no'}, "
        f"{arts.get('slo_transitions', 0)} SLO transition(s)")
    lines.append("")
    hyps = doc.get("hypotheses", [])
    if not hyps:
        lines.append("no hypothesis: the run dir carries no failure "
                     "evidence (nothing to diagnose, or nothing was "
                     "recorded).")
    else:
        lines.append("-- Ranked root-cause hypotheses --")
        for hyp in hyps:
            mark = "*" if hyp.get("rank") == 1 and \
                doc.get("identified") else " "
            lines.append(
                f"{mark}#{hyp.get('rank')} "
                f"[{hyp.get('confidence'):.2f}] "
                f"{hyp.get('cause')}: {hyp.get('title')}")
            for ev in hyp.get("evidence", []):
                lines.append(f"      evidence: {ev.get('ref')} — "
                             f"{ev.get('note')}")
            if hyp.get("symptoms"):
                lines.append("      symptoms: "
                             + ", ".join(hyp["symptoms"][:8])
                             + (" …" if len(hyp["symptoms"]) > 8
                                else ""))
            lines.append(f"      {hyp.get('explanation')}")
        lines.append("")
        if doc.get("identified"):
            lines.append(f"ROOT CAUSE: {doc.get('root_cause')} "
                         f"(confidence "
                         f"{hyps[0].get('confidence'):.2f})")
        else:
            lines.append("ROOT CAUSE: not identified (best hypothesis "
                         "below the "
                         f"{ROOT_CAUSE_THRESHOLD:.2f} threshold)")
    timeline = doc.get("timeline", [])
    if timeline:
        lines.append("")
        lines.append(f"-- Timeline (last {min(timeline_tail, len(timeline))} "
                     f"of {len(timeline) + doc.get('timeline_truncated', 0)}"
                     " entries) --")
        t0 = doc.get("window", {}).get("t_start") or \
            timeline[0].get("t", 0.0)
        for entry in timeline[-timeline_tail:]:
            dt = float(entry.get("t", 0.0)) - float(t0)
            lines.append(f"  +{dt:8.3f}s {entry.get('id'):<24} "
                         f"{entry.get('summary')}")
    return "\n".join(lines)
