"""Online drift / anomaly detection over stored telemetry series
(ISSUE 18).

Two cheap, jax-free detectors run over any series the tsdb stores:

* **EWMA z-score** — an exponentially weighted mean/variance pair per
  series; the score is the standardized distance of the newest value
  from the running estimate.  Catches level shifts and spikes.
* **Seasonal-naive residual** — the residual against the value one
  season ago (``season`` points back), itself standardized by an EWMA
  of residuals.  Catches "the daily pattern changed" drift that a
  plain EWMA absorbs.

The published ``drift_score{series}`` gauge is ``max(|z_ewma|,
|z_seasonal|) / z_threshold`` — >= 1.0 means drifting.  The zoo's own
anomaly-detection capability plugs in through ``model_hook`` (given
the recent window, return a score or ``None`` to defer to the
built-ins) — the platform dogfooding its model zoo on its own
telemetry, with the stdlib detectors as the always-available default.

CONTRACT: stdlib-only, loadable by file path (the ``aggregator.py``
contract) so ``obs_report --slo`` renders drift callouts jax-free.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "DriftDetector",
    "DriftWatch",
    "drift_report",
]


class DriftDetector:
    """Streaming detector for ONE series."""

    def __init__(self, *, alpha: float = 0.1, z_threshold: float = 3.0,
                 season: int = 0, min_points: int = 8,
                 window: int = 256):
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.season = int(season)
        self.min_points = int(min_points)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0
        self._res_n = 0
        self._res_mean = 0.0
        self._res_var = 0.0
        self._ring: deque = deque(maxlen=max(self.season, 1))
        self.recent: deque = deque(maxlen=int(window))
        self.last_score = 0.0
        self.peak_score = 0.0

    def _z(self, value: float, mean: float, var: float,
           n: int) -> float:
        if n < self.min_points:
            return 0.0
        sd = math.sqrt(max(var, 1e-18))
        # a flat-lined series (sd ~ 0) only drifts when the value
        # actually moves; guard against a 0/0 explosion on noise-free
        # constants
        if sd < 1e-9:
            return 0.0 if abs(value - mean) < 1e-9 else self.z_threshold * 2
        return (value - mean) / sd

    def observe(self, value: float) -> float:
        """Feed one point; returns the drift score (>= 1.0 drifting)."""
        value = float(value)
        self.recent.append(value)
        z_ewma = self._z(value, self.mean, self.var, self.n)
        z_seasonal = 0.0
        if self.season > 0 and len(self._ring) == self.season:
            residual = value - self._ring[0]
            z_seasonal = self._z(residual, self._res_mean,
                                 self._res_var, self._res_n)
            diff = residual - self._res_mean
            incr = self.alpha * diff
            self._res_mean += incr
            self._res_var = (1 - self.alpha) * (self._res_var
                                                + diff * incr)
            self._res_n += 1
        if self.season > 0:
            self._ring.append(value)
        # update the level estimate AFTER scoring the point against it
        diff = value - self.mean
        incr = self.alpha * diff
        self.mean += incr
        self.var = (1 - self.alpha) * (self.var + diff * incr)
        self.n += 1
        self.last_score = max(abs(z_ewma),
                              abs(z_seasonal)) / self.z_threshold
        self.peak_score = max(self.peak_score, self.last_score)
        return self.last_score


class DriftWatch:
    """Watches a set of series selectors over a store, one detector
    per concrete series, publishing ``drift_score{series}``.

    ``model_hook(series_key, recent_values) -> Optional[float]`` is
    the anomaly-model plug-in point; return ``None`` to keep the
    stdlib score."""

    def __init__(self, selectors: Sequence[str], *,
                 registry: Any = None,
                 model_hook: Optional[Callable[[str, List[float]],
                                               Optional[float]]] = None,
                 **detector_kwargs: Any):
        self.selectors = list(selectors)
        self.model_hook = model_hook
        self._detector_kwargs = detector_kwargs
        self._detectors: Dict[str, DriftDetector] = {}
        self._seen_until: Dict[str, float] = {}
        self.peak_at: Dict[str, float] = {}
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "drift_score",
                "drift score per watched series (>=1 drifting)",
                labels=("series",))

    def detector(self, key: str) -> DriftDetector:
        if key not in self._detectors:
            self._detectors[key] = DriftDetector(
                **self._detector_kwargs)
        return self._detectors[key]

    def observe_store(self, store: Any) -> Dict[str, float]:
        """Feed every not-yet-seen point of every watched series;
        returns the latest score per series."""
        scores: Dict[str, float] = {}
        for selector in self.selectors:
            for key, pts in store.query(selector).items():
                det = self.detector(key)
                seen = self._seen_until.get(key, float("-inf"))
                for t, v in pts:
                    if t <= seen:
                        continue
                    score = det.observe(v)
                    if score >= det.peak_score:
                        self.peak_at[key] = t
                    self._seen_until[key] = t
                score = det.last_score
                if self.model_hook is not None and det.recent:
                    hooked = self.model_hook(key, list(det.recent))
                    if hooked is not None:
                        score = float(hooked)
                        det.last_score = score
                scores[key] = score
                if self._gauge is not None:
                    self._gauge.labels(key).set(score)
                self._notify_watchdog(key, score)
        return scores

    @staticmethod
    def _notify_watchdog(key: str, score: float) -> None:
        """Feed the training watchdog's advisory drift hook when one
        is active.  Lazy, optional import: drift.py stays loadable by
        file path with nothing but the stdlib on the path."""
        try:
            from analytics_zoo_tpu.observability.watchdog import (
                get_active_watchdog)
        except ImportError:
            return
        wd = get_active_watchdog()
        if wd is not None and hasattr(wd, "observe_drift"):
            wd.observe_drift(key, score)


def drift_report(store: Any, selectors: Sequence[str], *,
                 threshold: float = 1.0,
                 **detector_kwargs: Any) -> List[Dict[str, Any]]:
    """Offline sweep: replay every matching series through a fresh
    detector and return the callouts sorted worst-first — the
    ``obs_report --slo`` drift section."""
    watch = DriftWatch(selectors, **detector_kwargs)
    scores = watch.observe_store(store)
    out = []
    for key, score in scores.items():
        det = watch.detector(key)
        out.append({
            "series": key,
            "score": round(score, 4),
            "peak_score": round(det.peak_score, 4),
            "peak_at": watch.peak_at.get(key),
            "drifting": det.peak_score >= threshold,
            "points": det.n,
            "mean": round(det.mean, 6),
            "last": round(det.recent[-1], 6) if det.recent else None,
        })
    out.sort(key=lambda d: -d["peak_score"])
    return out
