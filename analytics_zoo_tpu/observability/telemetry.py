"""Device telemetry: sample accelerator memory + runtime cache state
into registry gauges.

Answers "where does the memory go" — the half of the ROADMAP north-star
the step timers can't see.  On TPU, ``device.memory_stats()`` exposes
``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``; the CPU
backend returns ``None`` (every field is gated, never assumed).  Live
jax.Array census and jit-cache size come from public jax APIs where
they exist, skipped where they don't — telemetry must degrade to
"fewer gauges", never to an exception on a hot path.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from analytics_zoo_tpu.observability.metrics import (
    MetricsRegistry, get_registry)

log = logging.getLogger("analytics_zoo_tpu.observability")

# memory_stats keys worth exporting, mapped to gauge names
_MEM_KEYS = {
    "bytes_in_use": "device_bytes_in_use",
    "peak_bytes_in_use": "device_peak_bytes_in_use",
    "bytes_limit": "device_bytes_limit",
    "largest_free_block_bytes": "device_largest_free_block_bytes",
    "pool_bytes": "device_pool_bytes",
    "num_allocs": "device_num_allocs",
}


def _jit_cache_size() -> Optional[int]:
    """Compiled-executable cache entries, via whichever internal cache
    this jax version exposes; None when none are reachable."""
    try:
        from jax._src import pjit as _pjit
        for attr in ("_cpp_pjit_cache_fun_only",
                     "_cpp_pjit_cache_explicit_attributes",
                     "_pjit_lower_cached"):
            cache = getattr(_pjit, attr, None)
            if cache is None:
                continue
            if hasattr(cache, "cache_info"):
                return int(cache.cache_info().currsize)
            if hasattr(cache, "size"):
                return int(cache.size())
    except Exception:
        pass
    return None


# devices that have successfully reported memory_stats at least once
# in this process — a later failed poll on one of these marks its
# gauges STALE instead of silently freezing them (some backends drop
# memory_stats mid-run, e.g. across a tunneled-runtime reconnect).
# Guarded: the TelemetrySampler thread and direct callers (estimator
# per-epoch sampling, tests) may run a pass concurrently.
_reported_devices: set = set()
_reported_lock = threading.Lock()


def sample_device_telemetry(registry: Optional[MetricsRegistry] = None
                            ) -> Dict[str, float]:
    """One sampling pass: set the gauges and return what was sampled
    (a plain dict, handy for logging/tests).  Never raises — a backend
    where ``memory_stats()`` becomes unavailable mid-run degrades to
    stale-marked gauges (``device_telemetry_stale{device}=1`` while
    the last good values stay exported) rather than an exception
    escaping the sampler thread."""
    reg = registry if registry is not None else get_registry()
    sampled: Dict[str, float] = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return sampled

    for dev in devices:
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        label = str(getattr(dev, "id", dev))
        with _reported_lock:
            reported_before = label in _reported_devices
            if stats:
                _reported_devices.add(label)
        if not stats:
            if reported_before:
                # the device USED to report: keep the last-good gauge
                # values (scrapes still see them) but flag staleness
                # so dashboards/alerts don't trust a frozen number
                reg.gauge(
                    "device_telemetry_stale",
                    "1 when the device stopped reporting memory_stats "
                    "mid-run (its device_* gauges hold last-good "
                    "values)", labels=("device",)).labels(label).set(1)
                sampled[f"device_telemetry_stale{{{label}}}"] = 1.0
            continue
        if reported_before:
            reg.gauge(
                "device_telemetry_stale",
                "1 when the device stopped reporting memory_stats "
                "mid-run (its device_* gauges hold last-good values)",
                labels=("device",)).labels(label).set(0)
        for key, gname in _MEM_KEYS.items():
            if key in stats:
                reg.gauge(
                    gname, f"device memory_stats()[{key!r}]",
                    labels=("device",)).labels(label).set(stats[key])
                sampled[f"{gname}{{{label}}}"] = float(stats[key])

    # host-side census of live jax.Arrays (count + bytes): catches
    # leaked epoch caches / unreleased checkpoints even on backends
    # with no memory_stats
    try:
        import jax
        arrays = jax.live_arrays()
        nbytes = 0
        for a in arrays:
            try:
                nbytes += a.nbytes
            except Exception:
                continue
        reg.gauge("jax_live_arrays",
                  "live jax.Array objects in this process"
                  ).set(len(arrays))
        reg.gauge("jax_live_array_bytes",
                  "total bytes of live jax.Arrays (logical, pre-"
                  "sharding)").set(nbytes)
        sampled["jax_live_arrays"] = float(len(arrays))
        sampled["jax_live_array_bytes"] = float(nbytes)
    except Exception:
        pass

    size = _jit_cache_size()
    if size is not None:
        reg.gauge("jax_jit_cache_entries",
                  "compiled executables in the pjit cache").set(size)
        sampled["jax_jit_cache_entries"] = float(size)
    return sampled


class TelemetrySampler:
    """Background sampler: calls :func:`sample_device_telemetry` every
    ``interval_s`` until stopped.  Daemon thread, safe to abandon."""

    def __init__(self, interval_s: float = 10.0,
                 registry: Optional[MetricsRegistry] = None):
        self.interval_s = float(interval_s)
        self.registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()   # restartable after stop()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="zoo-telemetry-sampler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                sample_device_telemetry(self.registry)
            except Exception:
                log.exception("telemetry sample failed")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
