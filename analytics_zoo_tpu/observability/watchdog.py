"""Training-health watchdog: non-finite loss/grad detection, loss
divergence/plateau detection over a sliding window, and a stall
heartbeat — with a configurable policy.

The reference surfaced run health through driver logs and validation
summaries; a silently-NaN'd run was only visible when someone read the
loss curve.  Here the health signals are *first-class*: the jitted
train step folds a ``jnp.isfinite`` reduction over loss+grads into its
program and surfaces the flag through a host callback (the grad-norm
callback path); the driver loop feeds observed losses and heartbeats;
a background thread flags stalls when no step completes within a
deadline.  The policy decides what an unhealthy signal does:

* ``warn`` — structured log + metrics, training continues;
* ``checkpoint_and_halt`` — the Estimator snapshots through its
  checkpoint machinery and raises :class:`TrainingHalted` (which the
  failure-retry loop deliberately does NOT absorb — retrying a NaN'd
  step would replay the same poison).

Plateau and stall are *advisory* (always warn-only): halting a run for
a plateau would turn early stopping into a crash; a truly stalled loop
cannot run the halting code anyway, so the heartbeat thread's loud log
line and health gauge are the honest best-effort.

Metrics: ``train_nonfinite_total{source}``,
``watchdog_events_total{kind}``, ``train_health_status``
(0 healthy / 1 warned / 2 halt-pending).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Callable, Dict, List, Optional

from analytics_zoo_tpu.observability.metrics import (
    MetricsRegistry, get_registry)

log = logging.getLogger("analytics_zoo_tpu.observability")

HEALTHY, WARNED, HALT_PENDING = 0, 1, 2


class TrainingHalted(RuntimeError):
    """Raised by the ``checkpoint_and_halt`` policy after the halt
    snapshot is written.  Carries ``issue`` (the triggering event
    dict) so callers can render the reason without parsing the
    message."""

    def __init__(self, message: str, issue: Optional[Dict] = None):
        super().__init__(message)
        self.issue = issue or {}


class TrainingWatchdog:
    """Aggregates health signals from three producers — the in-jit
    finite-check callback (any thread), the driver loop
    (``beat``/``observe_loss``), and the stall monitor thread — into a
    queue of *issues* the driver polls between steps.

    ``clock`` is injectable for tests (defaults to
    ``time.monotonic``); all interval math uses it.
    """

    HALTING_KINDS = ("nonfinite", "divergence")

    def __init__(self, policy: Optional[str] = None,
                 window: Optional[int] = None,
                 min_delta: Optional[float] = None,
                 divergence: Optional[float] = None,
                 stall_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        from analytics_zoo_tpu.common.config import get_config
        cfg = get_config()
        self.policy = str(policy if policy is not None else cfg.get(
            "observability.watchdog_policy", "warn"))
        if self.policy not in ("warn", "checkpoint_and_halt"):
            raise ValueError(
                f"watchdog policy {self.policy!r}: expected 'warn' or "
                "'checkpoint_and_halt'")
        self.window = int(window if window is not None else cfg.get(
            "observability.watchdog_window", 50))
        self.min_delta = float(
            min_delta if min_delta is not None
            else cfg.get("observability.watchdog_min_delta", 1e-4))
        self.divergence = float(
            divergence if divergence is not None
            else cfg.get("observability.watchdog_divergence", 10.0))
        self.stall_timeout_s = float(
            stall_timeout_s if stall_timeout_s is not None
            else cfg.get("observability.watchdog_stall_s", 0.0))
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._issues: List[Dict] = []
        self._best = math.inf
        self._since_improve = 0
        self._observed = 0
        self._nonfinite_seen = 0
        self._diverged_fired = False
        self._stall_fired = False
        self._drift_fired: set = set()   # series currently in episode
        self._last_beat = clock()
        self._halted = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._set_status(HEALTHY)

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    def _set_status(self, value: int) -> None:
        try:
            self._reg().gauge(
                "train_health_status",
                "watchdog verdict: 0 healthy, 1 warned, 2 halt "
                "pending/halted").set(value)
        except Exception:
            pass

    def _push(self, kind: str, **detail) -> None:
        issue = {"kind": kind, **detail}
        with self._lock:
            self._issues.append(issue)
        try:
            self._reg().counter(
                "watchdog_events_total",
                "training-health events by kind",
                labels=("kind",)).labels(kind).inc()
        except Exception:
            pass
        # every watchdog episode is a flight event: the single
        # chokepoint all detectors (nonfinite/divergence/plateau/
        # stall/drift) funnel through
        try:
            from analytics_zoo_tpu.observability.flightrec import \
                record_event
            record_event("watchdog.episode", issue=kind, **detail)
        except Exception:   # noqa: BLE001 — forensics never halts health
            pass

    # ------------------------------------------------------- producers
    def beat(self) -> None:
        """A train step completed — feeds the stall deadline.  A beat
        after a flagged stall ends that episode and re-arms the
        detector for the next one."""
        with self._lock:     # vs the heartbeat thread's check_stall
            self._last_beat = self._clock()
            self._stall_fired = False

    def record_nonfinite(self, source: str = "step") -> None:
        """A non-finite loss/grad was detected (host-callback thread
        or a driver-side isfinite check).  The counter counts every
        occurrence; the ISSUE (and its warning log) is throttled —
        under the warn policy a permanently-NaN run would otherwise
        log once per step."""
        try:
            self._reg().counter(
                "train_nonfinite_total",
                "steps whose loss or gradients were non-finite",
                labels=("source",)).labels(source).inc()
        except Exception:
            pass
        with self._lock:
            self._nonfinite_seen += 1
            n = self._nonfinite_seen
        if n == 1 or n % 100 == 0:
            self._push("nonfinite", source=source, occurrences=n)

    def observe_loss(self, loss: float) -> None:
        """Feed a host-synced loss sample (logging crossings / epoch
        ends — never forces an extra device sync)."""
        try:
            loss = float(loss)
        except (TypeError, ValueError):
            return
        if not math.isfinite(loss):
            self.record_nonfinite("loss_sample")
            return
        self._observed += 1
        scale = max(abs(self._best), 1.0)
        if not math.isfinite(self._best) \
                or loss < self._best - self.min_delta * scale:
            # first finite sample seeds best (inf arithmetic would
            # otherwise NaN the comparison and freeze it forever)
            self._best = loss
            self._since_improve = 0
            self._diverged_fired = False
            return
        self._since_improve += 1
        if (not self._diverged_fired
                and math.isfinite(self._best)
                and loss - self._best > self.divergence * scale):
            self._diverged_fired = True   # once until a new best
            self._push("divergence", loss=loss, best=self._best,
                       factor=self.divergence)
        if self.window > 0 and self._since_improve >= self.window:
            self._since_improve = 0       # re-arm: one event per window
            self._push("plateau", best=self._best, window=self.window,
                       min_delta=self.min_delta)

    def observe_drift(self, series: str, score: float) -> None:
        """Advisory drift signal from ``observability/drift.py``: a
        normalized score (1.0 = at the detector's z-threshold) for a
        watched metric series.  Like plateau/stall, drift never halts
        — a distribution shift is a reason to LOOK at a run, not to
        kill it — but it rides the same issue queue and
        ``watchdog_events_total{kind="drift"}`` counter so the driver
        loop and obs_report surface it next to loss-health events.
        One event per episode: re-arms when the series drops back
        under threshold."""
        try:
            score = float(score)
        except (TypeError, ValueError):
            return
        if score < 1.0:
            self._drift_fired.discard(series)
            return
        if series in self._drift_fired:
            return
        self._drift_fired.add(series)
        self._push("drift", series=series, score=round(score, 3))

    # ---------------------------------------------------- stall monitor
    def check_stall(self) -> bool:
        """One stall check against the injectable clock (the heartbeat
        thread calls this; tests call it directly with a fake clock)."""
        if self.stall_timeout_s <= 0:
            return False
        # guard and flag-set under one lock so a beat() landing between
        # them can't be stomped by a stale stall verdict; _push takes
        # the same (non-reentrant) lock, so it runs after release
        with self._lock:
            if self._stall_fired:
                return False
            idle = self._clock() - self._last_beat
            if idle <= self.stall_timeout_s:
                return False
            self._stall_fired = True      # once per stall episode
        self._push("stall", idle_s=round(idle, 1),
                   deadline_s=self.stall_timeout_s)
        log.error(
            "training stall: no step completed in %.0fs (deadline "
            "%.0fs) — the loop may be hung in dispatch, a collective, "
            "or the input pipeline", idle, self.stall_timeout_s)
        self._set_status(HALT_PENDING if self._halted else WARNED)
        return True

    def start_stall_monitor(self) -> "TrainingWatchdog":
        """Daemon heartbeat thread; no-op when the deadline is 0."""
        if self.stall_timeout_s <= 0 or self._thread is not None:
            return self
        # arm the deadline NOW: time between construction and start
        # (checkpoint restore, cache placement) is setup, not a stall
        self.beat()
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    self.check_stall()
                except Exception:
                    log.exception("stall check failed")
                self._stop.wait(min(self.stall_timeout_s / 4.0, 10.0))

        self._thread = threading.Thread(
            target=run, daemon=True, name="zoo-train-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -------------------------------------------------------- consumer
    def poll(self) -> Optional[Dict]:
        """Drain the next pending issue (driver loop, between steps).

        Every issue is logged with structure; the return value is the
        first HALTING-ELIGIBLE issue when the policy is
        ``checkpoint_and_halt`` (the caller then snapshots and raises
        :class:`TrainingHalted`), else None."""
        halting = None
        while True:
            with self._lock:
                issue = self._issues.pop(0) if self._issues else None
            if issue is None:
                break
            log.warning("training-health event: %s", issue)
            self._set_status(WARNED)
            if (halting is None
                    and self.policy == "checkpoint_and_halt"
                    and issue["kind"] in self.HALTING_KINDS):
                halting = issue
        if halting is not None:
            self._halted = True
            self._set_status(HALT_PENDING)
        return halting

    def halted(self) -> bool:
        return self._halted


# -------------------------------------------------- process-wide hookup
_active_watchdog: Optional[TrainingWatchdog] = None
_active_lock = threading.Lock()


def set_active_watchdog(wd: Optional[TrainingWatchdog]
                        ) -> Optional[TrainingWatchdog]:
    """Install the watchdog the in-jit finite-check callback reports
    to; returns the previous one (restore it in a ``finally``)."""
    global _active_watchdog
    with _active_lock:
        prev = _active_watchdog
        _active_watchdog = wd
    return prev


def get_active_watchdog() -> Optional[TrainingWatchdog]:
    return _active_watchdog


def fold_finiteness_check(loss, grads) -> None:
    """IN-JIT: fold an ``isfinite(loss + Σ grads)`` reduction into the
    traced step (NaN/Inf propagate through the sums — one add-reduce
    per grad leaf) and surface the flag through
    :func:`record_step_finiteness`.  The single implementation both
    engines' step builders call, so the detection logic cannot
    diverge between them."""
    import jax
    import jax.numpy as jnp
    total = loss.astype(jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        total = total + jnp.sum(g).astype(jnp.float32)
    jax.debug.callback(record_step_finiteness, jnp.isfinite(total))


def record_step_finiteness(finite) -> None:
    """``jax.debug.callback`` target: the jitted step's folded
    ``isfinite(loss + Σ grads)`` flag lands here on host.  Must never
    raise (it runs on the callback thread inside the runtime)."""
    try:
        if bool(finite):
            return
        wd = get_active_watchdog()
        if wd is not None:
            wd.record_nonfinite("step")
        else:
            get_registry().counter(
                "train_nonfinite_total",
                "steps whose loss or gradients were non-finite",
                labels=("source",)).labels("step").inc()
    except Exception:
        pass
