"""Unified observability layer: metrics registry, span tracing, device
telemetry, and a scrape endpoint.

The measurement substrate for the ROADMAP north-star "runs as fast as
the hardware allows": one process-wide :class:`MetricsRegistry` that
training, serving, and inference all instrument into; a
:class:`Tracer` whose ``span("name")`` blocks export as Chrome-trace
JSON (Perfetto); :func:`sample_device_telemetry` pulling
``device.memory_stats()`` into gauges; and :class:`MetricsServer`
exposing it all over HTTP ``/metrics`` (Prometheus text exposition)
without any third-party dependency.

Quick use::

    from analytics_zoo_tpu.observability import (
        get_registry, span, start_metrics_server)

    reqs = get_registry().counter("my_requests_total", "requests")
    with span("handle", route="/predict"):
        reqs.inc()
    start_metrics_server(port=9090)   # scrape :9090/metrics
"""

from analytics_zoo_tpu.observability.metrics import (
    DEFAULT_BUCKETS,
    EPOCH_BUCKETS,
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from analytics_zoo_tpu.observability.tracing import (
    Tracer,
    get_tracer,
    reset_tracer,
    span,
)
from analytics_zoo_tpu.observability.telemetry import (
    TelemetrySampler,
    sample_device_telemetry,
)
from analytics_zoo_tpu.observability.exporter import (
    MetricsServer,
    start_metrics_server,
)
from analytics_zoo_tpu.observability.diagnostics import (
    CompileMonitor,
    get_compile_monitor,
    publish_mfu,
    reset_compile_monitor,
    step_attribution_histogram,
)
from analytics_zoo_tpu.observability.watchdog import (
    TrainingHalted,
    TrainingWatchdog,
    get_active_watchdog,
    set_active_watchdog,
)
from analytics_zoo_tpu.observability.aggregator import (
    ClusterAggregator,
    WorkerSource,
    flush_worker_observability,
    init_worker_observability,
    merge_requests,
    merge_snapshots,
    merge_traces,
    reset_worker_observability,
    straggler_report,
)
from analytics_zoo_tpu.observability.reqtrace import (
    TRACE_FIELD,
    TRACE_HEADER,
    RequestLog,
    RequestTimeline,
    TraceContext,
    get_request_log,
    merge_timeline_dicts,
    reset_request_log,
)
from analytics_zoo_tpu.observability.collectives import (
    estimate_train_step_collectives,
    record_step_collectives,
)
from analytics_zoo_tpu.observability.tsdb import (
    SeriesStore,
    TsdbSampler,
    TsdbWriter,
    flush_active_tsdb,
    get_active_tsdb,
    init_tsdb,
    reset_tsdb,
)
from analytics_zoo_tpu.observability.slo import (
    BurnWindow,
    SloEngine,
    SloObjective,
    SloStatus,
    evaluate_timeline,
    load_slo_yaml,
    parse_slo_specs,
)
from analytics_zoo_tpu.observability.drift import (
    DriftDetector,
    DriftWatch,
    drift_report,
)
from analytics_zoo_tpu.observability.flightrec import (
    EVENT_KINDS,
    FlightRecorder,
    flush_active_flightrec,
    get_active_flightrec,
    init_flightrec,
    record_event,
    reset_flightrec,
)
from analytics_zoo_tpu.observability.incident import (
    diagnose,
    render_incident,
    write_incident,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "EPOCH_BUCKETS",
    "MetricsRegistry",
    "get_registry",
    "reset_registry",
    "Tracer",
    "get_tracer",
    "reset_tracer",
    "span",
    "TelemetrySampler",
    "sample_device_telemetry",
    "MetricsServer",
    "start_metrics_server",
    "CompileMonitor",
    "get_compile_monitor",
    "reset_compile_monitor",
    "publish_mfu",
    "step_attribution_histogram",
    "TrainingHalted",
    "TrainingWatchdog",
    "get_active_watchdog",
    "set_active_watchdog",
    "ClusterAggregator",
    "WorkerSource",
    "flush_worker_observability",
    "init_worker_observability",
    "merge_requests",
    "merge_snapshots",
    "merge_traces",
    "reset_worker_observability",
    "straggler_report",
    "TRACE_FIELD",
    "TRACE_HEADER",
    "RequestLog",
    "RequestTimeline",
    "TraceContext",
    "get_request_log",
    "merge_timeline_dicts",
    "reset_request_log",
    "estimate_train_step_collectives",
    "record_step_collectives",
    "SeriesStore",
    "TsdbSampler",
    "TsdbWriter",
    "flush_active_tsdb",
    "get_active_tsdb",
    "init_tsdb",
    "reset_tsdb",
    "BurnWindow",
    "SloEngine",
    "SloObjective",
    "SloStatus",
    "evaluate_timeline",
    "load_slo_yaml",
    "parse_slo_specs",
    "DriftDetector",
    "DriftWatch",
    "drift_report",
    "EVENT_KINDS",
    "FlightRecorder",
    "flush_active_flightrec",
    "get_active_flightrec",
    "init_flightrec",
    "record_event",
    "reset_flightrec",
    "diagnose",
    "render_incident",
    "write_incident",
]
