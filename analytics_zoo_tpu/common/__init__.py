from analytics_zoo_tpu.common.config import ZooConfig, get_config
from analytics_zoo_tpu.common.zoo_context import (
    init_zoo_context,
    get_zoo_context,
    ZooContext,
)
from analytics_zoo_tpu.common.triggers import (
    Trigger,
    EveryEpoch,
    MaxEpoch,
    MaxIteration,
    SeveralIteration,
    MinLoss,
    MaxScore,
    TriggerAnd,
    TriggerOr,
)

__all__ = [
    "ZooConfig",
    "get_config",
    "init_zoo_context",
    "get_zoo_context",
    "ZooContext",
    "Trigger",
    "EveryEpoch",
    "MaxEpoch",
    "MaxIteration",
    "SeveralIteration",
    "MinLoss",
    "MaxScore",
    "TriggerAnd",
    "TriggerOr",
]
