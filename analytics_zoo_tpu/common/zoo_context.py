"""Context initialisation — the ``NNContext`` equivalent.

Reference behavior (NNContext.scala:133-149 ``initNNContext``): create or
fetch the SparkContext with zoo conf defaults, set MKL env vars per
engine type, version-check, then ``Engine.init`` discovers the node and
core topology.  TPU-natively the "engine" is JAX/XLA and the topology is
the device mesh, so ``init_zoo_context``:

1. resolves the layered config (``ZooConfig``),
2. initialises ``jax.distributed`` when a multi-host environment is
   detected (the Engine.init analogue),
3. builds the default ``jax.sharding.Mesh`` (ICI×DCN axes),
4. applies numeric policy (matmul precision, default dtypes).

Like the reference, it is idempotent: repeated calls return the live
context (``getOrCreate`` semantics).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

import jax

from analytics_zoo_tpu.common.config import ZooConfig, set_config
from analytics_zoo_tpu.parallel import mesh as mesh_lib

log = logging.getLogger("analytics_zoo_tpu")


class ZooContext:
    """Live runtime context: config + mesh + process topology."""

    def __init__(self, config: ZooConfig, mesh):
        self.config = config
        self.mesh = mesh
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.local_devices = jax.local_devices()
        self.devices = jax.devices()

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0

    def __repr__(self):
        return (f"ZooContext(devices={self.num_devices}, "
                f"processes={self.process_count}, "
                f"mesh={dict(self.mesh.shape)})")


_context: Optional[ZooContext] = None


def init_zoo_context(conf: Optional[Dict[str, Any]] = None,
                     conf_file: Optional[str] = None,
                     mesh_shape: Optional[Dict[str, int]] = None,
                     name: str = "Analytics Zoo TPU") -> ZooContext:
    """Create (or return) the global context.

    Mirrors ``init_nncontext`` (pyzoo nncontext.py:104): conf may carry
    any dotted config key; ``mesh_shape`` is an axis→size dict, e.g.
    ``{"data": 8}`` or ``{"data": -1, "model": 4}``.
    """
    global _context
    if _context is not None:
        return _context

    # Programmatic sets made BEFORE context init (get_config().set)
    # carry over; explicit init conf wins on conflicts.
    from analytics_zoo_tpu.common import config as config_mod
    prior = getattr(config_mod._global_config, "_programmatic", None) \
        if config_mod._global_config is not None else None
    merged = {**(prior or {}), **(conf or {})}
    config = ZooConfig(conf_file=conf_file, overrides=merged or None)
    set_config(config)

    logging.basicConfig(level=getattr(logging, str(config.get("log.level")),
                                      logging.INFO))

    # Multi-host bring-up (the Engine.init role): only when the standard
    # coordinator env is present and more than one process is declared.
    n_proc = int(os.environ.get("ZOO_TPU_NUM_PROCESSES", "1"))
    if n_proc > 1 and os.environ.get("ZOO_TPU_COORDINATOR"):
        jax.distributed.initialize(
            coordinator_address=os.environ["ZOO_TPU_COORDINATOR"],
            num_processes=n_proc,
            process_id=int(os.environ.get("ZOO_TPU_PROCESS_ID", "0")),
        )

    jax.config.update("jax_default_matmul_precision",
                      str(config.get("dtype.matmul_precision")))

    if mesh_shape is None:
        raw = config.get("mesh.shape")
        if raw and raw != "auto":
            mesh_shape = {}
            for part in str(raw).split(","):
                ax, s = part.split(":")
                mesh_shape[ax.strip()] = int(s)
    mesh = mesh_lib.create_mesh(mesh_shape)

    _context = ZooContext(config, mesh)

    # Cluster observability plane: when the launcher handed us a run
    # dir, stamp this worker's immutable host/process_index labels on
    # the registry, start its metrics endpoint on the injected port,
    # and (host 0) attach the cluster aggregator.  Best-effort — a
    # broken metrics port must never stop training.
    if os.environ.get("ZOO_TPU_RUN_DIR"):
        try:
            from analytics_zoo_tpu.observability.aggregator import (
                init_worker_observability)
            init_worker_observability(
                process_index=_context.process_index)
        except Exception:
            log.exception("cluster observability bring-up failed")

    log.info("%s initialised: %r", name, _context)
    return _context


def get_zoo_context() -> ZooContext:
    """Return the live context, initialising with defaults if needed."""
    if _context is None:
        return init_zoo_context()
    return _context


def reset_zoo_context() -> None:
    """Drop the global context (test helper)."""
    global _context
    _context = None
