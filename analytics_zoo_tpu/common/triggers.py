"""Trigger DSL controlling when training ends / validates / checkpoints.

Reference: ``ZooTrigger`` (zoo/common/ZooTrigger.scala:26-60) extends
BigDL's Trigger with slice-epoch awareness — ``EveryEpoch`` fires on
epoch boundaries even when one "epoch" is split into ``numSlice``
sub-epochs by DiskFeatureSet (FeatureSet.scala:585-662).

Triggers are predicates over an immutable ``TrainingState`` snapshot, so
they compose (`TriggerAnd`/`TriggerOr`) and stay trivially serialisable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class TrainingState:
    """Driver-side scalar state the engine maintains between steps."""
    epoch: int = 0            # completed epochs
    iteration: int = 0        # completed global steps
    slice_index: int = 0      # within-epoch slice (DiskFeatureSet analogue)
    num_slices: int = 1
    epoch_finished: bool = False   # true at an epoch boundary
    last_loss: float = float("inf")
    best_score: Optional[float] = None
    last_score: Optional[float] = None


class Trigger:
    def __call__(self, state: TrainingState) -> bool:
        raise NotImplementedError

    def __and__(self, other: "Trigger") -> "Trigger":
        return TriggerAnd(self, other)

    def __or__(self, other: "Trigger") -> "Trigger":
        return TriggerOr(self, other)


class EveryEpoch(Trigger):
    """Fires at true epoch boundaries (slice-aware, ZooTrigger.scala:31)."""

    def __call__(self, state: TrainingState) -> bool:
        return state.epoch_finished and (state.slice_index == 0)


class MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = int(max_epoch)

    def __call__(self, state: TrainingState) -> bool:
        return state.epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = int(max_iteration)

    def __call__(self, state: TrainingState) -> bool:
        return state.iteration >= self.max_iteration


class SeveralIteration(Trigger):
    """Fires every ``interval`` iterations (ZooTrigger.scala:50)."""

    def __init__(self, interval: int):
        assert interval > 0
        self.interval = int(interval)

    def __call__(self, state: TrainingState) -> bool:
        return state.iteration > 0 and state.iteration % self.interval == 0


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = float(min_loss)

    def __call__(self, state: TrainingState) -> bool:
        return state.last_loss < self.min_loss


class MaxScore(Trigger):
    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def __call__(self, state: TrainingState) -> bool:
        return state.last_score is not None and state.last_score > self.max_score


class TriggerAnd(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TrainingState) -> bool:
        return all(t(state) for t in self.triggers)


class TriggerOr(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TrainingState) -> bool:
        return any(t(state) for t in self.triggers)
