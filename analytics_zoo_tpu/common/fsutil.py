"""Small filesystem helpers shared by the jax-free control-plane
modules (supervisor, heartbeats, serving port discovery)."""

from __future__ import annotations

import os


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` atomically (pid-unique tmp +
    ``os.replace``): a reader never sees a torn file, and two
    processes racing on the same path on a shared filesystem cannot
    interleave into one tmp file or rename a partially-written one.
    OSError propagates — callers own their degrade/log policy."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Bytes twin of :func:`atomic_write_text` — same pid-unique tmp +
    ``os.replace`` contract for binary payloads (pickles, npz blobs).
    OSError propagates — callers own their degrade/log policy."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
